#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput (fwd+bwd+SGD update) on one
TPU chip, the headline metric of BASELINE.md (reference: 109 img/s train
on a K80 at bs32, ``example/image-classification/README.md:154``).

Runs the fused single-program train step in mixed precision (bf16
activations over fp32 master weights) and reports achieved model FLOP/s
and %MFU against the chip's bf16 peak alongside the reference-comparable
img/s metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Partial snapshots stream to stderr after each phase, and the shared
``bench_util`` watchdog (``--watchdog SEC`` / env
``MXNET_BENCH_WATCHDOG``, default 420, 0 to disable) prints the partial
line to stdout and exits 0 if the run wedges — so a hung backend init
still yields a parseable artifact instead of rc=124 with nothing.

The default sweep is sized to finish inside the watchdog: ResNet-50 at
one batch size plus the transformer MFU row.  The AlexNet/Inception-v3
flagship rows are opt-in via ``--all-models`` (they add two full
compile+measure cycles), ``--sweep`` adds the ResNet batch sweep, and
``--piped`` the record-fed epoch run.

Usage: bench.py [batch] [--fp32] [--sweep] [--all-models]
                [--piped (opt-in long run)] [--watchdog SEC]
"""
import json
import sys
import time

sys.path.insert(0, ".")

import bench_util

# the run's (partial) result — filled in phase by phase so a watchdog
# fire, a budget expiry (MXNET_BENCH_BUDGET_S), or an operator reading
# stderr mid-run still gets a usable line
_RESULT = {}


def _emit_partial():
    """Progress snapshot to stderr after each phase (stdout stays ONE
    final JSON line)."""
    print(json.dumps({"partial": True, **_RESULT}), file=sys.stderr,
          flush=True)

# fwd+bwd model FLOPs per 224x224 image for ResNet-50 under the standard
# MFU convention (multiply-add = 2 FLOPs, the same convention as the
# chip's peak spec): fwd ≈ 4.1 GMACs → 8.2 GFLOPs, train ≈ 3x fwd.
# Cross-checked against XLA's cost analysis of the compiled step, which
# reports ~24.0e9/img for fwd+bwd+SGD.  (Rounds 1-2 used 12.3e9 — the
# MAC=1 count — understating MFU 2x vs the peak's MAC=2 convention.)
TRAIN_FLOPS_PER_IMG = 24.6e9

# bf16 peak TFLOP/s by TPU generation (public spec sheets)
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _peak_flops(device):
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return None


def _measure(step, shapes, batch, iters=20):
    import jax
    import jax.numpy as jnp
    import numpy as np

    params, aux, states = step.init_state(shapes)
    rng = jax.random.PRNGKey(0)
    batch_dict = {
        "data": jax.random.normal(rng, shapes["data"], "float32"),
        "softmax_label": jnp.zeros(shapes["softmax_label"], "float32"),
    }
    # AOT compile FIRST, measured separately: compile_s stops being
    # silently folded into the warmup step, and the persistent cache
    # (MXNET_COMPILE_CACHE_DIR) makes it near-zero on a repeat run
    compile_s = bench_util.timed_compile(step, shapes, _RESULT)
    # XLA's own FLOP count of the step (MAC=2 convention, includes
    # fwd+bwd+optimizer) — the honest numerator for MFU.  The AOT path
    # recorded it already; otherwise take it from a host-side lower()
    # (no second backend compile — lower() is tracing only).
    xla_flops = (step.compile_stats or {}).get("flops")
    if xla_flops is None:
        try:
            lowered = step._jit_step.lower(
                params, aux, states, batch_dict, rng, step.lr,
                jnp.asarray(1, "int32"))
            ca = lowered.cost_analysis()
            ca = ca[0] if isinstance(ca, list) else ca
            xla_flops = float(ca.get("flops", 0.0)) or None
        except Exception:
            pass
    # warmup (compiles lazily when the AOT form was unavailable);
    # completion is forced with a host fetch because block_until_ready
    # does not synchronize through the axon tunnel
    params, aux, states, out = step(params, aux, states, batch_dict, rng)
    float(np.asarray(out[0][0, 0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, aux, states, out = step(params, aux, states, batch_dict, rng)
    float(np.asarray(out[0][0, 0]))  # forces the whole dependency chain
    return batch * iters / (time.perf_counter() - t0), xla_flops


def _bench_model(sym, batch, compute_dtype, image_shape=(3, 224, 224),
                 iters=20):
    """img/s for one model config on the current chip."""
    from mxnet_tpu.fused import TrainStep

    step = TrainStep(
        sym, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                          "rescale_grad": 1.0 / batch},
        compute_dtype=compute_dtype)
    shapes = {"data": (batch,) + tuple(image_shape),
              "softmax_label": (batch,)}
    return _measure(step, shapes, batch, iters=iters)


def _measure_piped(step, shapes, batch, iters=20, threads=8):
    """img/s for the same step fed by ImageRecordIter from a generated
    .rec — the end-to-end number all reference baselines are
    (docs/how_to/perf.md: every published img/s is pipeline-fed).
    Returns (img_s, pipeline_mb_s): the second is the raw JPEG MB/s the
    feeder sustained."""
    import os
    import tempfile

    import numpy as np

    import mxnet_tpu as mx

    cache = os.path.join(tempfile.gettempdir(), "mxtpu_bench_rec")
    rec = os.path.join(cache, "bench224.rec")
    n_imgs = 2048
    if not os.path.exists(rec):
        from PIL import Image

        os.makedirs(cache, exist_ok=True)
        rs = np.random.RandomState(0)
        w = mx.recordio.MXRecordIO(rec, "w")
        import io as _io

        for i in range(n_imgs):
            arr = (rs.rand(224, 224, 3) * 255).astype("uint8")
            buf = _io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG", quality=90)
            hdr = mx.recordio.IRHeader(0, float(i % 1000), i, 0)
            w.write(mx.recordio.pack(hdr, buf.getvalue()))
        w.close()
    rec_bytes = os.path.getsize(rec)

    params, aux, states = step.init_state(shapes)
    import jax
    import jax.numpy as jnp
    import time as _t

    rng = jax.random.PRNGKey(0)

    # host->device bandwidth for a FRESH buffer (the piped path ships
    # one decoded uint8 batch per step; on the axon tunnel this is the
    # binding constraint, on a real TPU-VM PCIe it is not)
    probe = (np.random.rand(batch, 224, 224, 3) * 255).astype("uint8")
    t0 = _t.perf_counter()
    float(np.asarray(jnp.sum(jax.device_put(probe)[0, 0, 0])))
    put_mb_s = probe.nbytes / 1e6 / (_t.perf_counter() - t0)

    it = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 224, 224), batch_size=batch,
        preprocess_threads=threads, prefetch_buffer=4, shuffle=False)

    # feeder-only rate: decode + host augs, no device consumption (drop
    # the device work by reading only shapes) — measured over one epoch
    inner = it
    t0 = _t.perf_counter()
    n_dec = 0
    for b in inner:
        n_dec += batch
    decode_img_s = n_dec / (_t.perf_counter() - t0)
    inner.reset()
    # warmup: one epoch primes decode threads + compiles the step
    # (batches arrive fp32 NCHW already ON DEVICE — the augmenter tail
    # runs jitted per batch, so no host cast happens here)
    n_batches = 0
    for b in it:
        bd = {"data": b.data[0]._data,
              "softmax_label": b.label[0]._data}
        params, aux, states, out = step(params, aux, states, bd, rng)
        n_batches += 1
    float(np.asarray(out[0][0, 0]))
    it.reset()
    t0 = _t.perf_counter()
    seen = 0
    epochs = max(1, iters // n_batches)
    for _ in range(epochs):
        for b in it:
            bd = {"data": b.data[0]._data,
                  "softmax_label": b.label[0]._data}
            params, aux, states, out = step(params, aux, states, bd, rng)
            seen += batch
        it.reset()
    float(np.asarray(out[0][0, 0]))
    dt = _t.perf_counter() - t0
    mb_s = epochs * rec_bytes / 1e6 / dt
    return seen / dt, mb_s, decode_img_s, put_mb_s


def main():
    # watchdog + budget timer arm BEFORE the first jax import: backend
    # init can hang (driver handshake, stale TPU lockfile) and a bench
    # that dies with rc=124 and no JSON is useless to the driver — armed
    # here, a hung init still emits valid partial JSON and exits 0
    argv = sys.argv[1:]
    watchdog_s = None
    if "--watchdog" in argv:
        i = argv.index("--watchdog")
        watchdog_s = float(argv[i + 1])
        del argv[i:i + 2]
    bench_util.arm_watchdog(_RESULT, watchdog_s)
    bench_util.arm_budget(_RESULT)

    import jax

    from mxnet_tpu.models import resnet
    from mxnet_tpu.fused import TrainStep

    args = [a for a in argv if not a.startswith("--")]
    fp32 = "--fp32" in sys.argv
    compute_dtype = None if fp32 else "bfloat16"
    batches = [int(args[0])] if args else [512]
    if "--sweep" in sys.argv:
        batches = sorted(set(batches) | {64, 128, 256, 512})

    layout = "NHWC" if "--nhwc" in sys.argv else "NCHW"
    result = _RESULT
    result["metric"] = "resnet50_train_images_per_sec_per_chip"
    result["precision"] = "float32" if fp32 else "bf16+fp32-master"
    result["layout"] = layout
    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224), layout=layout)
    best = (0.0, None, None)
    for batch in batches:
        step = TrainStep(
            sym, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / batch},
            compute_dtype=compute_dtype)
        dshape = (batch, 3, 224, 224) if layout == "NCHW" \
            else (batch, 224, 224, 3)
        shapes = {"data": dshape, "softmax_label": (batch,)}
        img_s, xla_flops = _measure(step, shapes, batch)
        result.setdefault("sweep", {})[str(batch)] = round(img_s, 2)
        _emit_partial()
        if img_s > best[0]:
            best = (img_s, batch, xla_flops)

    img_s, batch, xla_flops = best
    flops_per_img = (xla_flops / batch) if xla_flops else TRAIN_FLOPS_PER_IMG
    achieved = img_s * flops_per_img
    # peak table is bf16; fp32 peak differs per generation, so report
    # MFU only for the bf16 path
    peak = None if fp32 else _peak_flops(jax.devices()[0])
    baseline = 109.0  # K80 bs32 train img/s, BASELINE.md
    result.update({
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / baseline, 2),
        "batch_size": batch,
        "achieved_tflops": round(achieved / 1e12, 2),
        "flops_accounting": "xla_cost_analysis" if xla_flops
                            else "analytic_mac2",
        "mfu_pct": round(100 * achieved / peak, 2) if peak else None,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    })
    _emit_partial()
    # secondary metric: the MXU-bound transformer workload, where the
    # framework's compute ceiling shows (ResNet-50@224 is HBM-bound on
    # this hardware generation — see README).  Runs EARLY — right after
    # the headline metric — so the MFU row the roadmap tracks survives
    # a watchdog/budget cut that strands the longer optional phases.
    # Skipped under --fp32.
    if not fp32 and "--resnet-only" not in sys.argv:
        try:
            import bench_transformer

            tf = bench_transformer.measure(argv=[])
            result["transformer_tokens_per_sec"] = tf["value"]
            result["transformer_mfu_pct"] = tf["mfu_pct"]
            result["transformer_model"] = tf["model"]
            result["transformer_attn_peak_bytes"] = \
                tf.get("attn_peak_bytes")
        except Exception as exc:  # keep the primary metric robust
            result["transformer_error"] = str(exc)[:200]
        _emit_partial()
    # ZeRO A/B row: the sharded update's state shrink (~1/N per
    # replica), the ZeRO-3 at-rest param shrink + step-rate ratios vs
    # the replicated update, over the local device mesh
    # (bench_fit.measure_zero_ab; skipped when the host exposes a
    # single device).  Cheap MLP config — the claim under test is the
    # collective swap, not model FLOPs.
    if not fp32 and "--resnet-only" not in sys.argv:
        try:
            import bench_fit

            zsym = bench_fit.build_sym(512, 1024, 10)
            zrow = bench_fit.measure_zero_ab(zsym, 64, 512)
            for k, v in zrow.items():
                result[k] = v
        except Exception as exc:  # keep the primary metric robust
            result["zero_ab_error"] = str(exc)[:200]
        _emit_partial()
    # composed-plan A/B row: pure DP vs tp(2) x zero3 vs pipe(2) —
    # per-replica params/opt-state bytes, step ratios and gather
    # traffic under ONE ParallelPlan declaration
    # (bench_fit.measure_plan_ab; skipped below 4 devices)
    if not fp32 and "--resnet-only" not in sys.argv:
        try:
            import bench_fit

            psym = bench_fit.build_sym(512, 1024, 10)
            prow = bench_fit.measure_plan_ab(psym, 64, 512)
            for k, v in prow.items():
                result[k] = v
        except Exception as exc:  # mxlint: disable=MX008 — the one-JSON-line contract survives a failed A/B row
            result["plan_ab_error"] = str(exc)[:200]
        _emit_partial()
    # data-plane summary row: multiprocess decode pool vs the GIL-bound
    # thread pool over real JPEGs (bench_fit.measure_decode_ab has the
    # full A/B; small config here — the claim under test is decode
    # scaling, not record volume)
    if not fp32 and "--resnet-only" not in sys.argv:
        try:
            import bench_fit

            drow = bench_fit.measure_decode_ab(n_images=128, epochs=1)
            result["decode_pool_speedup"] = drow["decode_pool_speedup"]
            result["decode_pool_images_per_sec"] = \
                drow["decode_pool_images_per_sec"]
            result["data_workers"] = drow["data_workers"]
        except Exception as exc:  # keep the primary metric robust
            result["decode_ab_error"] = str(exc)[:200]
        _emit_partial()
    # serving summary row: continuous-batching speedup over serial plus
    # the continuous tokens/s and tail TTFT (bench_serve.py has the
    # full per-policy breakdown and the bit-exactness/KV-flat probes)
    if not fp32 and "--resnet-only" not in sys.argv:
        try:
            import bench_serve

            sv = bench_serve.measure(argv=[])
            result["serving_speedup_vs_serial"] = sv["value"]
            result["serving_tokens_per_sec"] = sv["tokens_per_sec"]
            result["serving_ttft_p99_s"] = sv["continuous_ttft_p99_s"]
            result["serving_bitexact"] = sv["bitexact"]
            # speculative-decoding A/B row (spec-on vs spec-off on the
            # low-concurrency rig; bench_serve.py has the full record)
            result["serving_spec_speedup"] = sv["spec_speedup"]
            result["serving_spec_bitexact"] = sv["bitexact_spec"]
            result["serving_spec_acceptance_rate"] = sv["acceptance_rate"]
            result["serving_spec_tokens_per_verify_step"] = \
                sv["tokens_per_verify_step"]
            # hybrid long-context row (window+SSM stack vs full
            # attention at fixed pool bytes; bench_serve.py asserts the
            # 2x capacity bar and the O(1) latency flatness)
            result["serving_window_capacity_ratio"] = \
                sv["window_capacity_ratio"]
            result["serving_window_latency_ratio_32k_over_4k"] = \
                sv["window_latency_ratio_32k_over_4k"]
            # network-edge row (real-socket gateway soak under chaos;
            # bench_serve.py asserts zero-lost, bit-exactness, and the
            # clean drain)
            result["serving_socket_goodput_rps"] = sv["gw_goodput_rps"]
            result["serving_socket_ttft_p50_delta_s"] = \
                sv["gw_ttft_p50_delta_s"]
            result["serving_socket_drain_clean"] = sv["gw_drain_clean"]
        except Exception as exc:  # keep the primary metric robust
            result["serving_error"] = str(exc)[:200]
        _emit_partial()
    # the BASELINE distributed-scaling flagships (docs/how_to/
    # perf.md:157-167: alexnet bs256 483.37 img/s, inception-v3 bs32
    # 29.62 img/s on K80) — single-chip rows so BENCH anchors more than
    # one model family.  OPT-IN via --all-models: two extra
    # compile+measure cycles do not fit the default watchdog budget
    # alongside the headline rows (the round-5 lesson).
    if not fp32 and "--all-models" in sys.argv:
        try:
            from mxnet_tpu.models import alexnet, inception_v3

            alex_s, _ = _bench_model(alexnet.get_symbol(1000), 512,
                                     compute_dtype)
            result["alexnet_train_images_per_sec_per_chip"] = \
                round(alex_s, 2)
            result["alexnet_vs_baseline"] = round(alex_s / 483.37, 2)
            inc_s, _ = _bench_model(inception_v3.get_symbol(1000), 128,
                                    compute_dtype,
                                    image_shape=(3, 299, 299), iters=10)
            result["inception_v3_train_images_per_sec_per_chip"] = \
                round(inc_s, 2)
            result["inception_v3_vs_baseline"] = round(inc_s / 29.62, 2)
        except Exception as exc:  # keep the primary metric robust
            result["secondary_model_error"] = str(exc)[:200]
        _emit_partial()

    # end-to-end fed benchmark: the same step consuming ImageRecordIter
    # batches decoded from a real .rec (reference numbers are all
    # pipeline-fed).  OPT-IN via --piped: it generates a 2048-image .rec
    # on first use and runs whole epochs, which is the long pole of the
    # run and the usual place a wedged tunnel strands the whole result
    # (the watchdog bounds it either way).  The feeder emits NCHW fp32,
    # so the piped row is NCHW-only; fp32 mode has no piped row (the
    # piped step is the bf16 headline config) — skips are marked in the
    # JSON.
    want_piped = "--piped" in sys.argv and "--no-piped" not in sys.argv
    if want_piped and (fp32 or layout != "NCHW"):
        result["piped_skipped"] = "fp32 run" if fp32 else \
            "piped feeder is NCHW-only"
        want_piped = False
    if want_piped:
        try:
            step = TrainStep(
                sym, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "rescale_grad": 1.0 / batch},
                compute_dtype=compute_dtype)
            piped_iters = 20
            piped_s, mb_s, dec_s, put_mb_s = _measure_piped(
                step, {"data": (batch, 3, 224, 224),
                       "softmax_label": (batch,)}, batch,
                iters=piped_iters)
            import os as _os

            result["piped_images_per_sec"] = round(piped_s, 2)
            result["piped_vs_synthetic"] = round(piped_s / img_s, 4)
            result["input_pipeline_mb_per_sec"] = round(mb_s, 1)
            result["piped_decode_images_per_sec"] = round(dec_s, 1)
            result["piped_h2d_mb_per_sec"] = round(put_mb_s, 1)
            result["piped_host_cores"] = _os.cpu_count()
            # the binding constraint: min(decode rate, transfer rate)
            xfer_img_s = put_mb_s * 1e6 / (3 * 224 * 224)
            result["piped_bound"] = (
                "h2d-transfer" if xfer_img_s < dec_s else "host-decode")
        except Exception as exc:
            result["piped_error"] = str(exc)[:200]
        _emit_partial()

    result["step_s"] = round(batch / img_s, 4) if img_s else None
    result.update(bench_util.compile_summary())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
