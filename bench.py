#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput (fwd+bwd+SGD update) on one
TPU chip, the headline metric of BASELINE.md (reference: 109 img/s train
on a K80 at bs32, ``example/image-classification/README.md:154``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.models import resnet
    from mxnet_tpu.fused import TrainStep

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    dtype = "bfloat16" if "--bf16" in sys.argv else "float32"

    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224))
    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                       "rescale_grad": 1.0 / batch})
    shapes = {"data": (batch, 3, 224, 224), "softmax_label": (batch,)}
    params, aux, moms = step.init_state(shapes, dtype=dtype)

    rng = jax.random.PRNGKey(0)
    data = jax.random.normal(rng, shapes["data"], dtype)
    label = jnp.zeros(shapes["softmax_label"], "float32")
    batch_dict = {"data": data, "softmax_label": label}

    # warmup/compile; completion is forced with a host fetch because
    # block_until_ready does not synchronize through the axon tunnel
    params, aux, moms, out = step(params, aux, moms, batch_dict, rng)
    float(np.asarray(out[0, 0]))

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, aux, moms, out = step(params, aux, moms, batch_dict, rng)
    float(np.asarray(out[0, 0]))  # forces the whole dependency chain
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    baseline = 109.0  # K80 bs32 train img/s, BASELINE.md
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / baseline, 2),
    }))


if __name__ == "__main__":
    main()
