#!/usr/bin/env python
"""Independent A/B: the framework's fused ResNet-50 train step vs a
hand-rolled RAW-JAX implementation of the same step, on the same chip.

The raw side imports NOTHING from mxnet_tpu: its own pre-activation
ResNet-50 (same architecture as ``models/resnet.py`` — v2 bottleneck,
NCHW), its own BatchNorm (fp32 stats over bf16 activations), its own
SGD-momentum update (fp32 masters, bf16 compute casts, grad rescale
1/batch), its own jit with donated buffers.  If the framework step is
slower than this raw step by more than the noise floor, the gap is
framework overhead; if they tie, the framework's throughput ceiling is
the hardware/XLA roofline, not the framework.

Prints ONE JSON line: {"raw_img_s", "framework_img_s", "ratio", ...}.

Usage: bench_ab.py [batch] [--iters N] [--raw-only|--framework-only]
"""
import functools
import json
import sys
import time

sys.path.insert(0, ".")


# ---------------------------------------------------------------------------
# raw-JAX ResNet-50 (pre-activation v2, NCHW) — no mxnet_tpu imports
# ---------------------------------------------------------------------------

def _raw_modules():
    import jax
    import jax.numpy as jnp
    from jax import lax

    DIMNUMS = lax.conv_dimension_numbers(
        (1, 1, 1, 1), (1, 1, 1, 1), ("NCHW", "OIHW", "NCHW"))

    def conv(x, w, stride=1, pad=0):
        return lax.conv_general_dilated(
            x, w, window_strides=(stride, stride),
            padding=((pad, pad), (pad, pad)), dimension_numbers=DIMNUMS)

    def bn_train(x, gamma, beta, eps=2e-5, fix_gamma=False):
        # mirror of the framework's bf16 BN: fp32 batch stats via
        # E[x^2]-E[x]^2, bf16 scale/shift application
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        mean = jnp.mean(x, axis=(0, 2, 3), dtype=jnp.float32)
        mean_sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 2, 3))
        var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        bshape = (1, x.shape[1], 1, 1)
        g32 = g.astype(jnp.float32).reshape(bshape)
        inv = lax.rsqrt(var + eps).reshape(bshape)
        scale = (inv * g32).astype(x.dtype)
        shift = (beta.astype(jnp.float32).reshape(bshape)
                 - mean.reshape(bshape) * inv * g32).astype(x.dtype)
        return x * scale + shift, mean, var

    def maxpool(x, k=3, s=2, p=1):
        import numpy as np

        # init must be a host constant: a traced init breaks
        # reduce_window's linearization rule under jit(grad(...))
        return lax.reduce_window(
            x, np.array(-np.inf, x.dtype), lax.max,
            (1, 1, k, k), (1, 1, s, s),
            ((0, 0), (0, 0), (p, p), (p, p)))

    return conv, bn_train, maxpool


RESNET50_UNITS = [3, 4, 6, 3]
RESNET50_FILTERS = [64, 256, 512, 1024, 2048]


def raw_init(rng, num_classes=1000):
    """fp32 master parameters + BN aux stats for raw ResNet-50."""
    import jax
    import jax.numpy as jnp

    params, aux = {}, {}
    keys = iter(jax.random.split(rng, 256))

    def add_conv(name, cin, cout, k):
        fan_in = cin * k * k
        params[name + "_weight"] = jax.random.normal(
            next(keys), (cout, cin, k, k), "float32") * (2.0 / fan_in) ** 0.5

    def add_bn(name, c):
        params[name + "_gamma"] = jnp.ones((c,), "float32")
        params[name + "_beta"] = jnp.zeros((c,), "float32")
        aux[name + "_moving_mean"] = jnp.zeros((c,), "float32")
        aux[name + "_moving_var"] = jnp.ones((c,), "float32")

    add_bn("bn_data", 3)
    add_conv("conv0", 3, 64, 7)
    add_bn("bn0", 64)
    cin = 64
    for i, (n_units, filt) in enumerate(zip(RESNET50_UNITS,
                                            RESNET50_FILTERS[1:])):
        for j in range(n_units):
            name = "stage%d_unit%d" % (i + 1, j + 1)
            add_bn(name + "_bn1", cin)
            add_conv(name + "_conv1", cin, filt // 4, 1)
            add_bn(name + "_bn2", filt // 4)
            add_conv(name + "_conv2", filt // 4, filt // 4, 3)
            add_bn(name + "_bn3", filt // 4)
            add_conv(name + "_conv3", filt // 4, filt, 1)
            if j == 0:
                add_conv(name + "_sc", cin, filt, 1)
            cin = filt
    add_bn("bn1", cin)
    import jax.random as jrandom
    params["fc1_weight"] = jrandom.normal(
        next(keys), (num_classes, cin), "float32") * (1.0 / cin) ** 0.5
    params["fc1_bias"] = jnp.zeros((num_classes,), "float32")
    return params, aux


def raw_forward(p, x):
    """bf16 forward; returns (logits, new_bn_stats {name: (mean, var)})."""
    import jax.numpy as jnp

    conv, bn_train, maxpool = _raw_modules()
    stats = {}

    def bn(name, h, fix_gamma=False):
        out, mean, var = bn_train(h, p[name + "_gamma"], p[name + "_beta"],
                                  fix_gamma=fix_gamma)
        stats[name] = (mean, var)
        return out

    h = bn("bn_data", x, fix_gamma=True)
    h = conv(h, p["conv0_weight"], stride=2, pad=3)
    h = jnp.maximum(bn("bn0", h), 0)
    h = maxpool(h)
    cin = 64
    for i, (n_units, filt) in enumerate(zip(RESNET50_UNITS,
                                            RESNET50_FILTERS[1:])):
        for j in range(n_units):
            name = "stage%d_unit%d" % (i + 1, j + 1)
            stride = 1 if (i == 0 or j > 0) else 2
            a1 = jnp.maximum(bn(name + "_bn1", h), 0)
            b = conv(a1, p[name + "_conv1_weight"])
            b = jnp.maximum(bn(name + "_bn2", b), 0)
            b = conv(b, p[name + "_conv2_weight"], stride=stride, pad=1)
            b = jnp.maximum(bn(name + "_bn3", b), 0)
            b = conv(b, p[name + "_conv3_weight"])
            sc = h if j > 0 else conv(a1, p[name + "_sc_weight"],
                                      stride=stride)
            h = b + sc
            cin = filt
    h = jnp.maximum(bn("bn1", h), 0)
    h = jnp.mean(h, axis=(2, 3))  # global average pool
    logits = h @ p["fc1_weight"].T + p["fc1_bias"]
    return logits, stats


def make_raw_step(batch, momentum=0.9, bn_momentum=0.9):
    """jitted fused train step: fwd+bwd+SGD-momentum+BN-stat update,
    donated fp32 masters, bf16 compute — the raw mirror of
    ``mxnet_tpu.fused.TrainStep``."""
    import jax
    import jax.numpy as jnp

    def step(params, aux, mom, x, y, lr):
        def loss_fn(p):
            pc = {k: v.astype(jnp.bfloat16) for k, v in p.items()}
            logits, stats = raw_forward(pc, x.astype(jnp.bfloat16))
            logits32 = logits.astype(jnp.float32)
            logz = jax.nn.log_softmax(logits32, axis=-1)
            ce = -jnp.sum(jnp.take_along_axis(
                logz, y[:, None].astype(jnp.int32), axis=-1))
            return ce, stats

        grads, stats = jax.grad(loss_fn, has_aux=True)(params)
        new_params, new_mom = {}, {}
        fixed = {"bn_data_gamma"}  # fix_gamma head: pinned to 1
        for k in params:
            if k in fixed:
                new_params[k] = params[k]
                new_mom[k] = mom[k]
                continue
            g = grads[k] * (1.0 / batch)
            m = momentum * mom[k] - lr * g
            new_params[k] = params[k] + m
            new_mom[k] = m
        new_aux = {}
        for name, (mean, var) in stats.items():
            new_aux[name + "_moving_mean"] = (
                bn_momentum * aux[name + "_moving_mean"]
                + (1 - bn_momentum) * mean)
            new_aux[name + "_moving_var"] = (
                bn_momentum * aux[name + "_moving_var"]
                + (1 - bn_momentum) * var)
        return new_params, new_aux, new_mom

    return jax.jit(step, donate_argnums=(0, 1, 2))


def measure_raw(batch, iters=20):
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = jax.random.PRNGKey(0)
    params, aux = raw_init(rng)
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    x = jax.random.normal(rng, (batch, 3, 224, 224), "float32")
    y = jnp.zeros((batch,), "float32")
    step = make_raw_step(batch)
    params, aux, mom = step(params, aux, mom, x, y, 0.1)
    float(np.asarray(params["fc1_bias"][0]))  # force completion
    t0 = time.perf_counter()
    for _ in range(iters):
        params, aux, mom = step(params, aux, mom, x, y, 0.1)
    float(np.asarray(params["fc1_bias"][0]))
    return batch * iters / (time.perf_counter() - t0)


def measure_framework(batch, iters=20):
    import bench

    from mxnet_tpu.models import resnet

    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224), layout="NCHW")
    img_s, _ = bench._bench_model(sym, batch, "bfloat16", iters=iters)
    return img_s


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    batch = int(args[0]) if args else 512
    iters = 20
    if "--iters" in sys.argv:
        iters = int(sys.argv[sys.argv.index("--iters") + 1])

    result = {"metric": "resnet50_ab_raw_vs_framework", "batch_size": batch,
              "unit": "img/s"}
    if "--framework-only" not in sys.argv:
        result["raw_img_s"] = round(measure_raw(batch, iters), 2)
    if "--raw-only" not in sys.argv:
        result["framework_img_s"] = round(measure_framework(batch, iters), 2)
    if "raw_img_s" in result and "framework_img_s" in result:
        result["ratio_framework_over_raw"] = round(
            result["framework_img_s"] / result["raw_img_s"], 4)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
