#!/usr/bin/env python
"""Benchmark: end-to-end ``Module.fit`` throughput vs the pure fused-step
device rate on synthetic data.

The pure-step rate (``TrainStep`` fed pre-staged device batches in a
tight loop) is the ceiling; the pipeline-efficiency ratio says how much
of it the full training loop — iterator, host→device staging, metric
updates, callbacks — actually delivers.  The pipelined fit (device
prefetch + lazy metrics + scanned multi-step dispatch) should sit
close to 1.0; the unpipelined single-step loop
(``nopipeline_efficiency``) is the pre-pipeline training loop.  The
default regime is small-batch/deep-scan, where the per-batch overhead
the pipeline removes is the dominant gap on a shared-core CPU host;
on a real accelerator behind a host link, run larger batches with
``--host-work N`` so the hidden cost is the transfer + decode.

Prints ONE JSON line:
``{"metric": "fit_images_per_sec", "value", "pure_step_images_per_sec",
"pipeline_efficiency", "fit_nopipeline_images_per_sec",
"nopipeline_efficiency", ...}``

The feeder emulates a decode/augment input pipeline with a fixed slab
of numpy work per batch (``--host-work R`` tanh passes, measured and
reported as ``host_work_ms_per_batch``): that is the cost the device
prefetcher moves off the critical path, exactly as it would a JPEG
decoder.  ``--host-work 0`` benchmarks the bare iterator.

Usage: bench_fit.py [batch] [--steps-per-call K] [--epochs N]
                    [--metric-sync N] [--host-work R] [--skip-nopipe]
"""
import json
import sys
import time

sys.path.insert(0, ".")

import bench_util

# phase-by-phase partial result for the MXNET_BENCH_BUDGET_S emitter
_RESULT = {"metric": "fit_images_per_sec"}


def _flag_value(name, default):
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


def build_sym(feat, hidden, num_classes):
    import mxnet_tpu as mx

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax",
                                normalization="batch")


def measure_pure_step(sym, batch, feat, iters=60):
    """Device-rate ceiling: the fused step over one resident batch."""
    import jax
    import numpy as np

    from mxnet_tpu.fused import TrainStep

    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01,
                                       "rescale_grad": 1.0 / batch})
    shapes = {"data": (batch, feat), "softmax_label": (batch,)}
    # compile measured apart from the step rate (and served from the
    # persistent cache on a repeat run)
    bench_util.timed_compile(step, shapes, _RESULT,
                             key="pure_step_compile_s")
    params, aux, states = step.init_state(shapes)
    rng = jax.random.PRNGKey(0)
    bd = {"data": jax.random.normal(rng, shapes["data"], "float32"),
          "softmax_label": jax.numpy.zeros(shapes["softmax_label"],
                                           "float32")}
    params, aux, states, out = step(params, aux, states, bd, rng)
    float(np.asarray(out[0][0, 0]))  # compile + force
    t0 = time.perf_counter()
    for _ in range(iters):
        params, aux, states, out = step(params, aux, states, bd, rng)
    float(np.asarray(out[0][0, 0]))
    return batch * iters / (time.perf_counter() - t0)


def measure_fp8_ab(sym, batch, feat, steps=24, iters=40):
    """fp8 training A/B (``MXNET_FP8``): bf16 vs bf16-with-fp8-matmuls
    loss trajectories over identical batches and seeds, the max drift
    asserted under an explicit bound (the delayed-scaling recipe must
    TRACK the clean path, not just stay finite), plus the steady-state
    step-rate ratio.  On CPU the fake-cast pairs are exposed arithmetic
    next to small matmuls, so the ratio is the honesty row; on
    fp8-native hardware XLA folds each pair into a real fp8 operand
    (tools/fusion_audit.py --expect-fp8 checks the folds held)."""
    import os

    import jax
    import numpy as np

    from mxnet_tpu.fused import TrainStep

    shapes = {"data": (batch, feat), "softmax_label": (batch,)}
    rs = np.random.RandomState(3)
    bd = {"data": rs.randn(*shapes["data"]).astype("float32"),
          "softmax_label": rs.randint(
              0, 10, size=shapes["softmax_label"]).astype("float32")}
    lab = bd["softmax_label"].astype(int)

    def run(fp8):
        old = os.environ.get("MXNET_FP8")
        os.environ["MXNET_FP8"] = "on" if fp8 else "off"
        try:
            step = TrainStep(sym, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.05,
                                               "rescale_grad": 1.0 / batch},
                             compute_dtype="bfloat16")
            params, aux, states = step.init_state(shapes)
            rng = jax.random.PRNGKey(0)
            losses = []
            for i in range(steps):
                params, aux, states, out = step(
                    params, aux, states, bd, jax.random.fold_in(rng, i))
                p = np.asarray(out[0], dtype="float32")
                losses.append(float(-np.log(np.maximum(
                    p[np.arange(batch), lab], 1e-30)).mean()))
            jax.block_until_ready(params)
            t0 = time.perf_counter()
            for _ in range(iters):
                params, aux, states, out = step(params, aux, states, bd,
                                                rng)
            jax.block_until_ready(out[0])
            rate = batch * iters / (time.perf_counter() - t0)
            return losses, rate, step
        finally:
            if old is None:
                os.environ.pop("MXNET_FP8", None)
            else:
                os.environ["MXNET_FP8"] = old

    base_losses, base_rate, _ = run(False)
    fp8_losses, fp8_rate, fstep = run(True)
    drift = max(abs(a - b) for a, b in zip(base_losses, fp8_losses))
    drift_bound = 0.25
    out = {
        "fp8_sites": fstep._fp8_sites,
        "fp8_amax_history": int(np.asarray(
            fstep._hstate["fp8_hist"]).shape[-1]),
        "bf16_loss_first": round(base_losses[0], 5),
        "bf16_loss_final": round(base_losses[-1], 5),
        "fp8_loss_first": round(fp8_losses[0], 5),
        "fp8_loss_final": round(fp8_losses[-1], 5),
        "fp8_loss_drift_max": round(drift, 5),
        "fp8_loss_drift_bound": drift_bound,
        "bf16_images_per_sec": round(base_rate, 2),
        "fp8_images_per_sec": round(fp8_rate, 2),
        "fp8_step_ratio": round(fp8_rate / max(base_rate, 1e-9), 4),
    }
    assert fstep._fp8_sites and fstep._fp8_sites >= 3, \
        "fp8 route claimed %r matmul sites (expected every FC layer)" \
        % (fstep._fp8_sites,)
    assert drift <= drift_bound, \
        "fp8 loss trajectory drifted %.4f from bf16 (bound %.2f)" \
        % (drift, drift_bound)
    assert fp8_losses[-1] < fp8_losses[0], \
        "fp8 loss not decreasing: %r -> %r" % (fp8_losses[0],
                                               fp8_losses[-1])
    return out


def measure_zero_ab(sym, batch, feat, iters=30):
    """zero=off vs zero=on vs zero=3 A/B over the device mesh: step
    rate, the per-replica optimizer-state bytes (the ZeRO 1/N claim),
    the per-replica at-rest parameter bytes (the ZeRO-3 1/N claim), and
    the per-step gather traffic.  Adam, so the state is real (two
    moments per weight); skipped on a single-device host where the
    sharded update auto-declines."""
    import jax
    import numpy as np

    from mxnet_tpu.fused import TrainStep
    from mxnet_tpu.parallel import create_mesh

    ndev = len(jax.devices())
    if ndev < 2 or batch % ndev:
        return {}
    mesh = create_mesh({"data": ndev})
    out = {"zero_ndev": ndev}
    rates = {}
    for mode in ("off", "on", "3"):
        step = TrainStep(sym, optimizer="adam",
                         optimizer_params={"learning_rate": 0.125,
                                           "rescale_grad": 1.0 / batch},
                         mesh=mesh, zero=mode)
        shapes = {"data": (batch, feat), "softmax_label": (batch,)}
        params, aux, states = step.init_state(shapes)
        rng = jax.random.PRNGKey(0)
        bd = {"data": jax.random.normal(rng, shapes["data"], "float32"),
              "softmax_label": jax.numpy.zeros(shapes["softmax_label"],
                                               "float32")}
        params, aux, states, out_ = step(params, aux, states, bd, rng)
        float(np.asarray(out_[0][0, 0]))  # compile + force
        t0 = time.perf_counter()
        for _ in range(iters):
            params, aux, states, out_ = step(params, aux, states, bd, rng)
        float(np.asarray(out_[0][0, 0]))
        rates[mode] = batch * iters / (time.perf_counter() - t0)
        rep = step.memory_report(params, states)
        tag = "zero3" if mode == "3" else mode
        out["opt_state_bytes_%s" % tag] = int(rep["opt_state_bytes"])
        out["params_bytes_at_rest_%s" % tag] = \
            int(rep["params_bytes_per_replica"])
        out["gather_bytes_per_step_%s" % tag] = \
            int(rep["gather_bytes_per_step"])
        if mode == "on":
            out["update_gather_bytes"] = int(rep["update_gather_bytes"])
    out["zero_off_images_per_sec"] = round(rates["off"], 2)
    out["zero_on_images_per_sec"] = round(rates["on"], 2)
    out["zero3_images_per_sec"] = round(rates["3"], 2)
    out["zero_step_ratio"] = round(rates["on"] / rates["off"], 4)
    out["zero3_step_ratio"] = round(rates["3"] / rates["off"], 4)
    out["zero3_vs_zero1_step_ratio"] = round(rates["3"] / rates["on"], 4)
    out["zero_state_shrink"] = round(
        out["opt_state_bytes_off"] / max(1, out["opt_state_bytes_on"]), 3)
    out["zero3_params_shrink"] = round(
        out["params_bytes_at_rest_off"]
        / max(1, out["params_bytes_at_rest_zero3"]), 3)
    return out


def measure_plan_ab(sym, batch, feat, iters=20):
    """Composed-plan A/B over the local devices: pure DP (replicated)
    vs tp(2) x zero3 vs pipe(2) x stage-sharding.  Reports per-replica
    at-rest params/opt-state bytes (the composition's memory claim:
    tp x zero3 must land well under 1/model of pure DP), the step-rate
    ratios, and the per-step gather traffic.  Adam, so the state is
    real.  Skipped below 4 devices — the composed mesh needs a
    nontrivial (data, model) grid."""
    import jax
    import numpy as np

    from mxnet_tpu.fused import TrainStep
    from mxnet_tpu.parallel import (ParallelPlan, PipelineTrainStep,
                                    create_mesh, mesh_scope)

    ndev = len(jax.devices())
    if ndev < 4 or ndev % 2 or batch % ndev:
        return {}
    shapes = {"data": (batch, feat), "softmax_label": (batch,)}
    rng = jax.random.PRNGKey(0)
    bd = {"data": jax.random.normal(rng, shapes["data"], "float32"),
          "softmax_label": jax.numpy.zeros(shapes["softmax_label"],
                                           "float32")}
    out = {"plan_ndev": ndev}
    rates = {}
    plans = {
        "dp": ParallelPlan(data=ndev, zero="off"),
        "tp_zero3": ParallelPlan(data=ndev // 2, model=2, zero="3"),
    }
    for tag, plan in plans.items():
        step = TrainStep(sym, optimizer="adam",
                         optimizer_params={"learning_rate": 0.125,
                                           "rescale_grad": 1.0 / batch},
                         plan=plan)
        params, aux, states = step.init_state(shapes)
        params, aux, states, out_ = step(params, aux, states, bd, rng)
        float(np.asarray(out_[0][0, 0]))  # compile + force
        t0 = time.perf_counter()
        for _ in range(iters):
            params, aux, states, out_ = step(params, aux, states, bd,
                                             rng)
        float(np.asarray(out_[0][0, 0]))
        rates[tag] = batch * iters / (time.perf_counter() - t0)
        rep = step.memory_report(params, states)
        out["plan_%s" % tag] = plan.fingerprint(step.mesh)
        out["params_bytes_per_replica_%s" % tag] = \
            int(rep["params_bytes_per_replica"])
        out["opt_state_bytes_%s" % tag] = int(rep["opt_state_bytes"])
        out["gather_bytes_per_step_%s" % tag] = \
            int(rep["gather_bytes_per_step"])
        out["%s_images_per_sec" % tag] = round(rates[tag], 2)
    # pipeline row: stage-sharded packed buffers over a 2-way 'pipe'
    # mesh — each replica holds 1/pipe of params AND opt state (the
    # stage assignment is the sharding), the zero-1-like column
    mesh = create_mesh({"pipe": 2}, devices=jax.devices()[:2])
    with mesh_scope(mesh):
        pstep = PipelineTrainStep(
            sym, optimizer="adam",
            optimizer_params={"learning_rate": 0.125,
                              "rescale_grad": 1.0 / batch},
            mesh=mesh, n_microbatches=4)
        params, aux, states = pstep.init_state(shapes)
        params, aux, states_, _ = pstep(params, aux, states, bd, rng)
        jax.block_until_ready(pstep._packed_params)
        t0 = time.perf_counter()
        for _ in range(iters):
            _, _, _, out_ = pstep(None, None, None, bd, rng)
        jax.block_until_ready(out_)
        rates["pp"] = batch * iters / (time.perf_counter() - t0)
        packed_bytes = 0
        for buf in (pstep._packed_params, pstep._packed_states):
            if buf is None:
                continue
            shard = next(iter(buf.addressable_shards))
            packed_bytes += int(shard.data.size * shard.data.itemsize)
        out["pp_zero1_images_per_sec"] = round(rates["pp"], 2)
        out["params_opt_bytes_per_replica_pp_zero1"] = packed_bytes
        # each stage row pads to the LARGEST stage, so a param-lopsided
        # split (compute-balanced cuts) erodes the 1/pipe claim — the
        # balance ratio says how much of the resident bytes is padding
        totals = [pk.total for pk in pstep._param_packers]
        out["pp_stage_param_balance"] = round(
            min(totals) / max(1, max(totals)), 4)
    dp_total = (out["params_bytes_per_replica_dp"]
                + out["opt_state_bytes_dp"])
    tp_total = (out["params_bytes_per_replica_tp_zero3"]
                + out["opt_state_bytes_tp_zero3"])
    out["plan_tp_zero3_step_ratio"] = round(rates["tp_zero3"]
                                            / rates["dp"], 4)
    out["plan_pp_step_ratio"] = round(rates["pp"] / rates["dp"], 4)
    out["plan_tp_zero3_state_shrink"] = round(dp_total / max(1, tp_total),
                                              3)
    out["plan_pp_state_shrink"] = round(
        dp_total / max(1, out["params_opt_bytes_per_replica_pp_zero1"]),
        3)
    return out


def make_host_work_iter(base, repeats):
    """Wrap a DataIter with a fixed slab of numpy work per batch — the
    stand-in for decode/augment cost.  Runs on whatever thread consumes
    the iterator, so the device prefetcher absorbs it."""
    import numpy as np

    import mxnet_tpu as mx

    class HostWorkIter(mx.io.DataIter):
        def __init__(self):
            super().__init__(base.batch_size)

        provide_data = property(lambda self: base.provide_data)
        provide_label = property(lambda self: base.provide_label)

        def reset(self):
            base.reset()

        def next(self):
            batch = next(base)
            arr = batch.data[0].asnumpy()
            for _ in range(repeats):
                arr = np.tanh(arr)
            return mx.io.DataBatch(data=[mx.nd.array(arr)],
                                   label=batch.label, pad=batch.pad,
                                   index=batch.index)

    return HostWorkIter()


def measure_fit(sym, X, y, batch, epochs, pipeline, steps_per_call,
                metric_sync, host_work=0):
    """img/s of the full Module.fit loop, timed over the epochs after
    the first.  Compile no longer hides in epoch 0 — fit's AOT warmup
    thread compiles before the epoch loop and the wall time lands in
    ``compile_s`` (profiler.compile_events) — but epoch 0 stays excluded
    so prefetch-ring and metric warmup don't skew the steady rate."""
    import mxnet_tpu as mx

    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    if host_work:
        it = make_host_work_iter(it, host_work)
    mod = mx.mod.Module(sym, context=mx.cpu())
    marks = []

    def epoch_cb(epoch, sym_, arg_params, aux_params):
        marks.append(time.perf_counter())

    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.01},
            epoch_end_callback=epoch_cb,
            prefetch_to_device=pipeline,
            steps_per_call=steps_per_call,
            metric_sync_period=metric_sync)
    imgs_per_epoch = (X.shape[0] // batch) * batch
    if steps_per_call > 1:
        # the packed iterator drops a trailing partial group
        n_steps = (X.shape[0] // batch // steps_per_call) * steps_per_call
        imgs_per_epoch = n_steps * batch
    return imgs_per_epoch * (len(marks) - 1) / (marks[-1] - marks[0])


def measure_ckpt_save(sym, X, y, batch, saves=5):
    """Main-thread cost per ``CheckpointManager.save``, synchronous vs
    ``MXNET_CKPT_ASYNC``-style background writes.  The async path should
    only pay the device→host snapshot; serialization + SHA-256 + fsync
    move to the ``mxtpu-ckpt-writer`` thread.  ``flush()`` between saves
    is off the clock — it stands in for the training steps that separate
    real checkpoints (back-to-back saves would serialize on the depth-1
    writer bound)."""
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint as ckpt

    it = mx.io.NDArrayIter(X[:batch * 2], y[:batch * 2], batch_size=batch)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.01})
    out = {}
    for mode, async_w in (("sync", False), ("async", True)):
        with tempfile.TemporaryDirectory() as d:
            mgr = ckpt.CheckpointManager(d, prefix="bench", keep=2,
                                         async_writes=async_w)
            mgr.save(mod, epoch=0)  # warm the path
            mgr.flush()
            total = 0.0
            for e in range(1, saves + 1):
                t0 = time.perf_counter()
                mgr.save(mod, epoch=e)
                total += time.perf_counter() - t0
                mgr.flush()
            out["ckpt_save_%s_ms" % mode] = round(total / saves * 1e3, 3)
    if out.get("ckpt_save_async_ms"):
        out["ckpt_async_speedup"] = round(
            out["ckpt_save_sync_ms"] / out["ckpt_save_async_ms"], 3)
    return out


def measure_migration(sym, X, y, batch):
    """Live-elasticity A/B: the in-memory plan migration (quiesce /
    re-form / reshard / resume, ``mxnet_tpu.parallel.elastic``) against
    the checkpoint-restart it replaces — save + fresh module rebuild +
    manifest restore onto the same new plan.  Both sides pay the fused
    step's lazy recompile on their first post-switch step (it lands in
    ``compile_s``, not here), so this measures the control-path
    downtime the migration actually removes: process-free mesh re-form
    and host-memory reshard vs a full checkpoint round trip plus module
    re-bind.  ``migration_speedup`` = restart_s / downtime_s."""
    import tempfile

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint as ckpt
    from mxnet_tpu.parallel.elastic import ElasticCoordinator, ScaleEvent

    ndev = len(jax.devices())
    if ndev >= 4 and batch % 4 == 0:
        old_spec, new_spec = "data=4,zero=off", "data=2,model=2,zero=off"
    elif ndev >= 2 and batch % 2 == 0:
        old_spec, new_spec = "data=2,zero=off", "data=1,zero=off"
    else:
        return {}
    it = mx.io.NDArrayIter(X[:batch * 4], y[:batch * 4], batch_size=batch)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="adam",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.01}, plan=old_spec)
    out = {}
    with tempfile.TemporaryDirectory() as d:
        mgr = ckpt.CheckpointManager(d, prefix="mig", async_writes=False)
        # untimed warm-up: the migration side runs first and would
        # otherwise be charged the cold costs (first host transfer,
        # first manifest write) that the restart side then skips
        mgr.save(mod, epoch=0, nbatch=0)
        mgr.flush()
        mgr.load()
        coord = ElasticCoordinator(num_workers=1, rank=0,
                                   install_signal=False)
        event = ScaleEvent(num_workers=1, plan=new_spec,
                           reason="bench A/B", source="manifest")
        report = coord.migrate(mod, event, epoch=1, nbatch=0,
                               train_data=it, checkpoint=mgr)
        out["migration_downtime_s"] = report["downtime_s"]
        for key, val in report["phases"].items():
            out["migration_%s_ms" % key[:-2]] = round(val * 1e3, 3)
        out["migration_old_plan"] = report["old_plan"]["fingerprint"]
        out["migration_new_plan"] = report["new_plan"]["fingerprint"]

        # baseline: the restart path onto the SAME new plan — final save
        # (the dying job's handoff), manifest restore, fresh module
        # re-bind, optimizer-state reinstall, data fast-forward.  No
        # process spawn is charged, so the baseline flatters restarts.
        t0 = time.perf_counter()
        mgr.save(mod, epoch=1, nbatch=0)
        mgr.flush()
        state = mgr.load()
        it2 = mx.io.NDArrayIter(X[:batch * 4], y[:batch * 4],
                                batch_size=batch)
        mod2 = mx.mod.Module(sym, context=mx.cpu())
        mod2.bind(data_shapes=it2.provide_data,
                  label_shapes=it2.provide_label, for_training=True)
        mod2.init_params(arg_params=state.arg_params,
                         aux_params=state.aux_params)
        mod2.init_optimizer(optimizer="adam",
                            optimizer_params={"learning_rate": 0.01},
                            plan=new_spec)
        mod2._restore_from(state)
        mod2._fast_forward_data(it2, state.epoch, state.nbatch)
        out["ckpt_restart_s"] = round(time.perf_counter() - t0, 6)
    out["migration_speedup"] = round(
        out["ckpt_restart_s"] / max(1e-9, out["migration_downtime_s"]), 3)
    return out


def measure_decode_ab(n_images=256, hw=64, batch=32, workers=None,
                      epochs=2):
    """Data-plane A/B over one real-JPEG record file: the classic
    thread-pool ``ImageIter`` (GIL-bound decode) vs the multiprocess
    ``DataServiceIter`` decode pool, same augmenter chain (rand-crop +
    mirror + normalize) both sides.  The pool should scale with cores
    where the thread pool serializes on the GIL."""
    import os
    import tempfile

    import numpy as np

    from mxnet_tpu import recordio
    from mxnet_tpu.base import get_env
    from mxnet_tpu.data_service import DataServiceIter
    from mxnet_tpu.image import (CreateAugmenter, ImageIter,
                                 RecordImageLoader)

    workers = int(workers if workers is not None
                  else get_env("MXNET_DATA_WORKERS", 0, int))
    workers = workers or min(4, os.cpu_count() or 1)
    shape = (3, hw - 8, hw - 8)  # rand-crop leaves room to move

    def aug():
        return CreateAugmenter(shape, rand_crop=True, rand_mirror=True,
                               mean=True, std=True)

    def run(iterator):
        sum(1 for _ in iterator)  # warm epoch: pools up, caches hot
        t0 = time.perf_counter()
        total = 0
        for _ in range(epochs):
            iterator.reset()
            total += sum(b.data[0].shape[0] for b in iterator)
        return total / (time.perf_counter() - t0)

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "bench")
        rs = np.random.RandomState(0)
        rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                         "w")
        for i in range(n_images):
            img = (rs.rand(hw, hw, 3) * 255).astype("uint8")
            rec.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i % 10), i, 0), img,
                quality=95))
        rec.close()

        it = ImageIter(batch, shape, path_imgrec=prefix + ".rec",
                       aug_list=aug())
        thread_rate = run(it)
        it.close()

        record = recordio.MXIndexedRecordIO(prefix + ".idx",
                                            prefix + ".rec", "r")
        loader = RecordImageLoader(shape, record=record, aug_list=aug())
        svc = DataServiceIter(loader, batch, seed=0, num_workers=workers)
        try:
            pool_rate = run(svc)
        finally:
            svc.close()
    return {
        "data_workers": workers,
        "decode_thread_images_per_sec": round(thread_rate, 2),
        "decode_pool_images_per_sec": round(pool_rate, 2),
        "decode_pool_speedup": round(pool_rate / max(thread_rate, 1e-9),
                                     3),
    }


def measure_input_attribution(sym, X, y, batch, epochs, host_work=0):
    """Input-bound vs compute-bound attribution for the fit loop: wrap
    the feeder in an instrumented :class:`DevicePrefetchIter` (fit's
    ``prefetch_to_device`` is idempotent at ``steps_per_call=1``, so it
    reuses the wrapper), and split each delivered batch's wall time into
    the consumer's staging-ring wait (input starvation — the decode +
    host→device path couldn't keep up) vs everything else (device step,
    metrics, callbacks).  ``input_bound_frac`` near 0 means the ring hid
    the input pipeline entirely; near 1 means fit is input-bound and
    decode workers, not device FLOPs, are the lever."""
    import mxnet_tpu as mx

    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    if host_work:
        it = make_host_work_iter(it, host_work)
    dev = mx.io.DevicePrefetchIter(it)
    mod = mx.mod.Module(sym, context=mx.cpu())
    marks = []

    def epoch_cb(epoch, sym_, arg_params, aux_params):
        if not marks:  # time + attribute only the post-warmup epochs
            dev.reset_stage_stats()
        marks.append(time.perf_counter())

    mod.fit(dev, num_epoch=epochs, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.01},
            epoch_end_callback=epoch_cb,
            prefetch_to_device=True, steps_per_call=1)
    wall = marks[-1] - marks[0]
    n = max(1, dev.batches_delivered)
    frac = min(1.0, dev.stage_wait_s / max(wall, 1e-9))
    return {
        "input_wait_ms_per_batch": round(dev.stage_wait_s / n * 1e3, 3),
        "step_ms_per_batch": round(wall / n * 1e3, 3),
        "input_bound_frac": round(frac, 4),
        "pipeline_bound": "input" if frac > 0.5 else "compute",
    }


def main():
    # watchdog + budget timers arm BEFORE the first jax/numpy touch:
    # backend init can hang, and an armed timer turns that into valid
    # partial JSON + exit 0 instead of the driver's rc=124/parsed=null
    bench_util.arm_watchdog(_RESULT)
    bench_util.arm_budget(_RESULT)

    import numpy as np

    import jax

    positional = [a for i, a in enumerate(sys.argv[1:], 1)
                  if not a.startswith("--")
                  and sys.argv[i - 1] not in ("--steps-per-call",
                                              "--epochs", "--metric-sync",
                                              "--host-work")]
    # default regime: small batch + deep scan.  On this CPU (one core)
    # host/device overlap cannot exist, so the benchmark targets the
    # overhead the pipeline REMOVES — per-batch Python dispatch and
    # metric synchronization — which dominates at small batch.  On a
    # real accelerator, larger batches with --host-work N measure the
    # hidden transfer+decode instead.
    batch = int(positional[0]) if positional else 64
    steps_per_call = _flag_value("--steps-per-call", 16)
    epochs = _flag_value("--epochs", 8)
    metric_sync = _flag_value("--metric-sync", 50)
    host_work = _flag_value("--host-work", 0)
    feat, hidden, classes = 512, 1024, 10
    n_batches = 32
    if n_batches % steps_per_call:
        n_batches += steps_per_call - n_batches % steps_per_call
    rs = np.random.RandomState(0)
    X = rs.randn(n_batches * batch, feat).astype("float32")
    y = rs.randint(0, classes, size=n_batches * batch).astype("float32")

    sym = build_sym(feat, hidden, classes)
    # the feeder's per-batch host cost, measured standalone
    arr = X[:batch]
    t0 = time.perf_counter()
    for _ in range(host_work):
        arr = np.tanh(arr)
    host_ms = (time.perf_counter() - t0) * 1e3

    pure_s = measure_pure_step(sym, batch, feat)
    _RESULT.update({
        "pure_step_images_per_sec": round(pure_s, 2),
        "pure_step_s": round(batch / pure_s, 6),
    })
    fit_s = measure_fit(sym, X, y, batch, epochs, pipeline=True,
                        steps_per_call=steps_per_call,
                        metric_sync=metric_sync, host_work=host_work)
    result = _RESULT
    result.update({
        "metric": "fit_images_per_sec",
        "value": round(fit_s, 2),
        "unit": "img/s",
        "pure_step_images_per_sec": round(pure_s, 2),
        "pipeline_efficiency": round(fit_s / pure_s, 4),
        "batch_size": batch,
        "steps_per_call": steps_per_call,
        "metric_sync_period": metric_sync,
        "host_work_ms_per_batch": round(host_ms, 2),
        "epochs_timed": epochs - 1,
        "batches_per_epoch": n_batches,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    })
    if "--skip-nopipe" not in sys.argv:
        nopipe_s = measure_fit(sym, X, y, batch, epochs, pipeline=False,
                               steps_per_call=1, metric_sync=1,
                               host_work=host_work)
        result["fit_nopipeline_images_per_sec"] = round(nopipe_s, 2)
        result["nopipeline_efficiency"] = round(nopipe_s / pure_s, 4)
        result["pipeline_speedup"] = round(fit_s / nopipe_s, 4)
    # where the wall time goes: input starvation vs device step
    result.update(measure_input_attribution(sym, X, y, batch,
                                            max(3, epochs // 2),
                                            host_work=host_work))
    # multiprocess decode pool vs thread pool over real JPEGs
    result.update(measure_decode_ab())
    # checkpoint write cost on the training thread, sync vs async
    result.update(measure_ckpt_save(sym, X, y, batch))
    # fp8 training A/B: loss-trajectory drift under the asserted bound
    # plus the step-rate ratio, bf16 vs bf16-with-fp8-matmuls
    result.update(measure_fp8_ab(sym, batch, feat))
    # ZeRO sharded update A/B: state bytes must shrink ~1/N at >=95%
    # of the replicated step rate
    result.update(measure_zero_ab(sym, batch, feat))
    # composed-plan A/B: pure DP vs tp x zero3 vs pipe x stage-sharding
    try:
        result.update(measure_plan_ab(sym, batch, feat))
    except Exception as exc:  # mxlint: disable=MX008 — the one-JSON-line contract survives a failed A/B row
        result["plan_ab_error"] = str(exc)[:200]
    # live elasticity: in-memory plan-migration downtime vs the
    # checkpoint-restart baseline it replaces
    try:
        result.update(measure_migration(sym, X, y, batch))
    except Exception as exc:  # mxlint: disable=MX008 — the one-JSON-line contract survives a failed A/B row
        result["migration_error"] = str(exc)[:200]
    # compile_s/step_s split + cache counters (fit's AOT warmup and the
    # pure-step AOT compile both record through profiler.compile_event)
    result.update(bench_util.compile_summary())
    # autotune provenance: which cached knobs (if any) the fused steps
    # were built under — MXNET_AUTOTUNE=1 + a tools/autotune.py record
    try:
        from mxnet_tpu import autotune
        result["autotune"] = autotune.provenance()
    except ImportError:
        result["autotune"] = []
    print(json.dumps(result))


if __name__ == "__main__":
    main()
