#!/usr/bin/env python
"""Serving benchmark: serial vs static-batch vs continuous batching.

Drives one :class:`mxnet_tpu.serve.InferenceSession` (compiled ONCE —
the same bucketed prefill + fixed-shape decode executables serve every
policy) through an identical Poisson open-loop arrival trace under the
three scheduler policies, and reports per-policy p50/p99 TTFT,
per-token latency, and tokens/s.  The headline metric is the
continuous-batching speedup over serial one-request-at-a-time serving.

Also certifies the serving acceptance criteria directly in the JSON:

* ``bitexact``           — paged decode logits == jitted full-context
                           reference forward (``assert_array_equal``).
* ``kv_pool_bytes_*``    — decode KV memory at step 1 vs step N
                           (identical: the pools are fixed buffers).
* ``executables`` / ``recompiles`` — compiled-executable count stays at
                           ``len(buckets) + 1`` with one trace each
                           (``len(buckets) + 3`` for the speculative
                           session).
* ``bitexact_spec``      — speculative decoding emits token streams
                           identical to non-speculative greedy decode
                           (exact acceptance), measured over a full
                           continuous-batching A/B whose
                           ``spec_speedup`` / ``acceptance_rate`` /
                           ``tokens_per_verify_step`` ride along.
* ``quant_speedup`` / ``quant_bytes_shrink`` / ``max_logit_drift``
                         — weight-only quantization A/B
                           (``ServeConfig.quant``): decode tokens/s
                           fp32 vs int8, at-rest param shrink, and the
                           teacher-forced logit drift, with the
                           speedup-or-shrink acceptance bar asserted
                           and per-precision bit-exactness
                           (``bitexact_quant``) re-proved on the
                           quantized tree.
* ``kv_capacity_multiplier`` / ``kv_max_logit_drift`` /
  ``bitexact_kv_quant``  — quantized KV-cache A/B
                           (``ServeConfig.kv_quant``): pages held at a
                           fixed pool-byte budget f32 vs int8/e4m3
                           codes, the teacher-forced logit drift
                           (bound asserted), the per-precision paged
                           oracle re-proved bit-exactly, and the
                           executable count held frozen.
* ``prefix_*`` / ``bitexact_prefix`` — prefix-cache A/B over a
                           shared-preamble trace (same executables, only
                           ``prefix_pages`` flips): hit rate, prefill
                           tokens saved, TTFT p50/p99 per side, with the
                           measured TTFT reduction on hits and
                           stream-level bit-exactness asserted.
* ``oversub_*`` / ``bitexact_oversub`` — admission A/B at an equal
                           undersized page pool: reservation vs
                           oversubscription peak concurrency (oversub
                           must sustain more requests in flight),
                           preemption/resume counts, and bit-identical
                           token streams across the two policies.
* ``closed_loop_*``      — closed-loop load generator (the scheduler's
                           ``followup`` hook holds concurrency constant)
                           under a TTFT budget: goodput-under-SLO and
                           SLO attainment.
* ``window_*``           — hybrid long-context A/B
                           (``ServeConfig.layers``/``window``): peak
                           concurrency of a window+SSM stack vs full
                           attention at a fixed pool-byte budget (>= 2x
                           asserted — the hybrid stack reserves no
                           pages), per-side goodput, and per-token
                           decode latency at pinned 4k vs 32k contexts
                           with the O(1) flatness bound asserted.
* ``soak_*``             — replicated-serving chaos soak
                           (``serve.ReplicaSet``, 3 replicas): one
                           replica chaos-killed mid-traffic, asserting
                           zero lost requests, bit-exact survivor
                           streams vs the fault-free baseline, typed
                           shed accounting, goodput >= 60% of baseline,
                           and the per-replica executable count frozen
                           across death + failover.
* ``gw_*``               — network-edge soak: the streaming asyncio
                           ``serve.Gateway`` over real sockets, same
                           trace + replica kill, with every 5th client
                           RST-crashing mid-stream — zero lost
                           requests, byte-identical completed streams,
                           state back at the cold snapshot, and a clean
                           graceful drain, all asserted.
* ``compile_report``     — ``compile_cache.write_artifact`` path for
                           the serving executable set
                           (pretty-print: ``tools/compile_report.py``).

Prints ONE JSON line.  Honors ``MXNET_BENCH_BUDGET_S`` (valid partial
JSON + exit 0) and always arms the ``bench_util`` watchdog.

Usage: bench_serve.py [--requests=N] [--max-new=N] [--quant=MODE]
                      [--kv-quant=MODE] [--watchdog SEC]
"""
import json
import sys
import time

sys.path.insert(0, ".")

import bench_util

_RESULT = {"metric": "serve_continuous_speedup_vs_serial"}


def _poisson_trace(n_requests, mean_gap_s, prompt_lens, max_new, seed):
    """Seeded open-loop arrival trace, replayed for every policy."""
    import numpy as np

    from mxnet_tpu.serve import Request

    rs = np.random.RandomState(seed)
    gaps = rs.exponential(mean_gap_s, size=n_requests)
    arrivals = np.cumsum(gaps) - gaps[0]  # first request at t=0
    reqs = []
    for i in range(n_requests):
        plen = int(prompt_lens[i % len(prompt_lens)])
        prompt = rs.randint(1, 127, size=plen).tolist()
        reqs.append(dict(rid=i, prompt=prompt, max_new=int(max_new),
                         arrival_s=float(arrivals[i])))
    return reqs


def _gw_client(port, spec, disconnect, out):
    """One socket client for the gateway soak: sleeps to its Poisson
    arrival offset, POSTs ``/v1/generate``, parses the chunked SSE
    stream, and records a TYPED terminal outcome.  ``disconnect``
    clients RST-close after the first token event (a crashed client —
    the gateway must cancel the decode and free its state)."""
    import socket
    import struct

    time.sleep(spec["arrival_s"])
    rec = {"outcome": "error", "ttft_s": None, "tokens": None}
    out[spec["rid"]] = rec
    t0 = time.perf_counter()
    try:
        sk = socket.create_connection(("127.0.0.1", port), timeout=300)
    except OSError:
        return
    try:
        body = json.dumps({"rid": spec["rid"], "prompt": spec["prompt"],
                           "max_new": spec["max_new"]}).encode()
        sk.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: bench\r\n"
                   b"Content-Length: " + str(len(body)).encode()
                   + b"\r\n\r\n" + body)
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sk.recv(65536)
            if not chunk:
                return
            buf += chunk
        head, _, buf = buf.partition(b"\r\n\r\n")
        status = int(head.split(None, 2)[1])
        if status == 429:
            rec["outcome"] = "shed"
            return
        if status != 200:
            rec["outcome"] = "http_%d" % status
            return
        while b"data: " not in buf:
            chunk = sk.recv(65536)
            if not chunk:
                return
            buf += chunk
        rec["ttft_s"] = time.perf_counter() - t0
        if disconnect:
            sk.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                          struct.pack("ii", 1, 0))
            rec["outcome"] = "disconnected"
            return
        while True:
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
        payload, events = b"", []
        while buf:  # de-chunk the HTTP body, then parse the SSE events
            size, _, buf = buf.partition(b"\r\n")
            n = int(size, 16)
            if n == 0:
                break
            payload += buf[:n]
            buf = buf[n + 2:]
        for line in payload.split(b"\n"):
            if line.startswith(b"data: "):
                events.append(json.loads(line[6:]))
        last = events[-1] if events else {}
        if last.get("done") and last.get("tokens") is not None:
            rec["outcome"] = "completed"
            rec["tokens"] = last["tokens"]
        elif last.get("done"):
            rec["outcome"] = "failed:%s" % last.get("error")
    except (OSError, ValueError):
        pass  # rec stays "error": the zero-lost assert surfaces it
    finally:
        sk.close()


def measure(argv=None):
    import numpy as np

    from mxnet_tpu import compile_cache, serve
    from mxnet_tpu.serve import model as serve_model

    argv = sys.argv if argv is None else argv
    n_requests = int(next((a.split("=")[1] for a in argv
                           if a.startswith("--requests=")), 16))
    max_new = int(next((a.split("=")[1] for a in argv
                        if a.startswith("--max-new=")), 16))

    cfg = serve.ModelConfig(vocab_size=128, num_layers=2, d_model=64,
                            num_heads=2, max_len=128)
    params = serve_model.init_params(cfg, seed=0)
    sconf = serve.ServeConfig(slots=8, page_size=16, buckets=(16, 32),
                              max_new=max_new, exact=True)
    t0 = time.perf_counter()
    sess = serve.InferenceSession(params, num_heads=cfg.num_heads,
                                  config=sconf)
    _RESULT["compile_s"] = round(time.perf_counter() - t0, 3)
    _RESULT["model"] = "%dL-d%d-V%d" % (cfg.num_layers, cfg.d_model,
                                        cfg.vocab_size)
    _RESULT["slots"] = sconf.slots
    _RESULT["buckets"] = list(sconf.buckets)
    _RESULT["executables"] = sorted(sess.executables)

    # -- acceptance probe 1: paged decode bit-exact vs reference ---------
    def ref_row(seq):
        return np.asarray(serve_model.reference_last_logits(
            sess.params, seq, cfg, sconf.page_size, exact=True))

    probe = list(np.random.RandomState(1).randint(1, 127, size=9))
    slot = sess.try_alloc(len(probe), 8)
    first, last_logits = sess.prefill(slot, probe)
    np.testing.assert_array_equal(last_logits, ref_row(probe))
    seq = list(probe) + [first]
    for _ in range(7):
        toks, logits = sess.step()
        np.testing.assert_array_equal(logits[slot], ref_row(seq))
        seq.append(toks[slot])
    sess.release(slot)
    _RESULT["bitexact"] = True

    # -- acceptance probe 2: KV memory flat in generated length ----------
    # the pools are fixed-shape buffers and the ONE decode executable
    # serves every step, so the watermark cannot move; record it from
    # both ends of a max-length generation to make that observable.
    mem = sess.memory_analysis("decode")
    _RESULT["decode_memory_analysis"] = mem
    slot = sess.try_alloc(16, max_new)
    sess.prefill(slot, list(range(1, 17)))
    step1_bytes = sess.cache.pool_bytes()
    sess.step()
    for _ in range(max_new - 2):
        sess.step()
    stepN_bytes = sess.cache.pool_bytes()
    sess.release(slot)
    _RESULT["kv_pool_bytes_step1"] = step1_bytes
    _RESULT["kv_pool_bytes_stepN"] = stepN_bytes
    assert step1_bytes == stepN_bytes, "KV pool bytes moved during decode"

    # -- the policy comparison -------------------------------------------
    trace = _poisson_trace(n_requests, mean_gap_s=0.002,
                           prompt_lens=(9, 14, 23, 30), max_new=max_new,
                           seed=2)
    policies = ("serial", "static", "continuous")
    for policy in policies:
        reqs = [serve.Request(**spec) for spec in trace]
        sched = serve.Scheduler(sess, policy=policy)
        done, makespan = sched.run(reqs)
        summary = serve.summarize(done, makespan)
        assert summary["failed"] == 0, "%s: %d requests failed" \
            % (policy, summary["failed"])
        assert summary["completed"] == n_requests
        for key, val in summary.items():
            _RESULT["%s_%s" % (policy, key)] = (
                round(val, 5) if isinstance(val, float) else val)

    speedup = (_RESULT["continuous_tokens_per_sec"]
               / max(_RESULT["serial_tokens_per_sec"], 1e-9))
    _RESULT["value"] = round(speedup, 2)
    _RESULT["unit"] = "x serial tokens/s"
    _RESULT["tokens_per_sec"] = _RESULT["continuous_tokens_per_sec"]

    # -- speculative decoding A/B ----------------------------------------
    # Self-speculative rig sharing the target family: damp the target's
    # upper-block out-projections so the first block carries most of the
    # prediction, then draft with the target truncated to that block
    # (layer-skip).  Acceptance is high for honest, reported reasons —
    # the damping is part of the rig, acceptance_rate is the measurement.
    import dataclasses as _dc

    # Speculation pays where decode is dispatch-bound, i.e. low slot
    # occupancy and long generations (a batch-8 decode step already
    # amortizes dispatch 8 ways, and draft prompt ingest must amortize
    # over the tokens it unlocks) — so the A/B runs its own
    # low-concurrency rig: 2 slots, short prompts, 64-token decodes.
    spec_k = int(next((a.split("=")[1] for a in argv
                       if a.startswith("--spec-k=")), 7))
    spec_max_new = 80
    damped = dict(params)
    for name in list(damped):
        blk = name.split("_", 1)[0]
        if (blk.startswith("blk") and int(blk[3:]) >= 1
                and name.endswith(("attn_out_weight", "ffn2_weight"))):
            damped[name] = damped[name] * 0.03
    spec_base = _dc.replace(sconf, slots=2, max_new=spec_max_new)
    spec_off = serve.InferenceSession(damped, num_heads=cfg.num_heads,
                                      config=spec_base)
    spec_conf = _dc.replace(spec_base, spec_k=spec_k, draft="layers:1")
    spec_on = serve.InferenceSession(damped, num_heads=cfg.num_heads,
                                     config=spec_conf)
    assert len(spec_on.executables) == len(spec_conf.buckets) + 3
    spec_trace = _poisson_trace(max(n_requests // 2, 8),
                                mean_gap_s=0.002,
                                prompt_lens=(9, 14),
                                max_new=spec_max_new, seed=4)
    spec_outs = {}
    for tag, spec_sess in (("spec_off", spec_off), ("spec_on", spec_on)):
        # one unmeasured warmup pass per rig irons out first-dispatch
        # jitter so the A/B compares steady-state serving
        serve.Scheduler(spec_sess, policy="continuous").run(
            [serve.Request(**spec) for spec in spec_trace[:2]])
        reqs = [serve.Request(**spec) for spec in spec_trace]
        done, makespan = serve.Scheduler(spec_sess,
                                         policy="continuous").run(reqs)
        summary = serve.summarize(done, makespan)
        assert summary["failed"] == 0, "%s: %d requests failed" \
            % (tag, summary["failed"])
        spec_outs[tag] = {r.rid: list(r.tokens) for r in done}
        for key in ("tokens_per_sec", "ttft_p50_s", "ttft_p99_s",
                    "total_tokens", "makespan_s"):
            val = summary[key]
            _RESULT["%s_%s" % (tag, key)] = (
                round(val, 5) if isinstance(val, float) else val)
    # the acceptance criterion: speculation may change only the cost of
    # a token stream, never its content
    _RESULT["bitexact_spec"] = spec_outs["spec_on"] == spec_outs["spec_off"]
    assert _RESULT["bitexact_spec"], "speculative decode drifted"
    rep = spec_on.spec_report()
    _RESULT["spec_k"] = spec_k
    _RESULT["acceptance_rate"] = round(rep["acceptance_rate"], 4)
    _RESULT["tokens_per_verify_step"] = round(
        rep["tokens_per_verify_step"], 3)
    _RESULT["spec_speedup"] = round(
        _RESULT["spec_on_tokens_per_sec"]
        / max(_RESULT["spec_off_tokens_per_sec"], 1e-9), 2)
    _RESULT["spec_executables"] = sorted(spec_on.executables)
    assert spec_on.fallback_count() == 0

    # -- weight-only quantization A/B ------------------------------------
    # Same model, same executable count, 1-byte weight codes: the A/B
    # measures steady-state decode tokens/s fp32 vs int8 and certifies
    # the two acceptance bars — either decode gets >= 1.15x faster or
    # the at-rest + gather bytes shrink >= 3.5x with throughput held —
    # plus an explicit logit-drift bound under teacher forcing.
    from mxnet_tpu import quantize as _quantize

    def _decode_tps(s, steps, cycles=4):
        # several alloc->decode cycles per measurement, timing only the
        # steady-state step loops: one cycle's window is ~steps decode
        # dispatches, too short to survive scheduler jitter
        rs = np.random.RandomState(7)
        total_dt, total_tok = 0.0, 0
        for _ in range(cycles):
            slots = []
            for _ in range(s.config.slots):
                sl = s.try_alloc(9, s.config.max_new)
                s.prefill(sl, rs.randint(1, 127, size=9).tolist())
                slots.append(sl)
            for _ in range(2):  # warmup: steady-state dispatch only
                s.step()
            t0 = time.perf_counter()
            for _ in range(steps):
                s.step()
            total_dt += time.perf_counter() - t0
            total_tok += s.config.slots * steps
            for sl in slots:
                s.release(sl)
        return total_tok / total_dt

    qmode = next((a.split("=")[1] for a in argv
                  if a.startswith("--quant=")), "int8")
    qsess = serve.InferenceSession(
        params, num_heads=cfg.num_heads,
        config=_dc.replace(sconf, quant=qmode))
    assert len(qsess.executables) == len(sconf.buckets) + 1
    _RESULT["quant"] = qmode
    _RESULT["weight_dtype"] = "float32"
    _RESULT["quant_weight_dtype"] = str(
        np.dtype(_quantize.quant_dtype(qmode)))

    # bit-exactness holds PER PRECISION: the quantized session must
    # match the jitted reference forward over its own quantized tree
    qslot = qsess.try_alloc(len(probe), 8)
    qfirst, qlogits = qsess.prefill(qslot, probe)
    np.testing.assert_array_equal(
        qlogits, np.asarray(serve_model.reference_last_logits(
            qsess.params, probe, cfg, sconf.page_size, exact=True)))
    qsess.release(qslot)
    _RESULT["bitexact_quant"] = True

    # logit drift vs fp32, teacher-forced so both sessions score the
    # SAME token sequence (greedy streams may diverge after one flip)
    drift = 0.0
    bslot = sess.try_alloc(len(probe), 8)
    qslot = qsess.try_alloc(len(probe), 8)
    bfirst, blog = sess.prefill(bslot, probe)
    _, qlog = qsess.prefill(qslot, probe)
    drift = max(drift, float(np.max(np.abs(qlog - blog))))
    for _ in range(6):
        qsess._slot_tokens[qslot] = sess._slot_tokens[bslot]
        btoks, blogs = sess.step()
        qtoks, qlogs = qsess.step()
        drift = max(drift, float(np.max(np.abs(qlogs[qslot]
                                               - blogs[bslot]))))
    sess.release(bslot)
    qsess.release(qslot)
    drift_bound = 0.25 if qmode == "int8" else 1.0
    _RESULT["max_logit_drift"] = round(drift, 5)
    _RESULT["logit_drift_bound"] = drift_bound
    assert drift <= drift_bound, \
        "%s logit drift %.4f exceeds %.2f" % (qmode, drift, drift_bound)

    # bytes: at-rest params and the decode executable's argument volume
    base_bytes = sess.params_bytes_at_rest()
    quant_bytes = qsess.params_bytes_at_rest()
    _RESULT["params_bytes_fp32"] = base_bytes
    _RESULT["params_bytes_quant"] = quant_bytes
    _RESULT["quant_bytes_shrink"] = round(base_bytes
                                          / max(quant_bytes, 1), 2)
    qmem = qsess.memory_analysis("decode")
    _RESULT["quant_decode_argument_bytes"] = qmem.get(
        "argument_size_in_bytes")
    _RESULT["decode_argument_bytes"] = mem.get("argument_size_in_bytes")

    # steady-state decode throughput A/B (same slot count, same step
    # count; the baseline reuses the already-warm main session).
    # Interleaved best-of-3: single passes swing ~20% under scheduler
    # noise at these tiny step times; alternating the sides and taking
    # each side's best damps both the noise and any slow load drift.
    ab_steps = max(4, min(12, max_new - 3))
    base_tps, quant_tps = 0.0, 0.0
    for _ in range(3):
        base_tps = max(base_tps, _decode_tps(sess, ab_steps))
        quant_tps = max(quant_tps, _decode_tps(qsess, ab_steps))
    _RESULT["decode_tokens_per_sec_fp32"] = round(base_tps, 1)
    _RESULT["decode_tokens_per_sec_quant"] = round(quant_tps, 1)
    _RESULT["quant_speedup"] = round(quant_tps / max(base_tps, 1e-9), 3)
    # Acceptance: EITHER decode gets >=1.15x faster (bandwidth-bound
    # accelerator rigs, where 4x-smaller weights shrink the HBM reads
    # each step) OR the at-rest/gather footprint shrinks >=3.5x with
    # throughput held.  "Held" is 0.82 here: on CPU the per-step
    # dequant is exposed arithmetic next to these tiny matmuls
    # (measured 0.86-0.94 across runs), a real but bounded cost — the
    # bar sits just under that band's floor so it catches a regression
    # (e.g. dequant falling out of the fused executable) without
    # flaking on scheduler noise.
    assert (_RESULT["quant_speedup"] >= 1.15
            or (_RESULT["quant_bytes_shrink"] >= 3.5
                and _RESULT["quant_speedup"] >= 0.82)), \
        "quant A/B: speedup %.3f, shrink %.2fx — neither bar met" \
        % (_RESULT["quant_speedup"], _RESULT["quant_bytes_shrink"])

    # -- quantized KV-cache A/B (int8/e4m3 pages) ------------------------
    # Same model, same executable set, 1-byte KV codes with one f32
    # scale per (layer, page, offset) row: the A/B certifies the
    # capacity multiplier at a fixed pool-byte budget, bounds the logit
    # drift vs the f32 cache under teacher forcing, and re-proves the
    # paged oracle bit-exactly at the cache's own precision.
    from mxnet_tpu.serve.kv_cache import PagedKVCache

    kvq = next((a.split("=")[1] for a in argv
                if a.startswith("--kv-quant=")), "int8")
    kvsess = serve.InferenceSession(
        params, num_heads=cfg.num_heads,
        config=_dc.replace(sconf, kv_quant=kvq))
    assert len(kvsess.executables) == len(sconf.buckets) + 1
    _RESULT["kv_quant"] = kvq
    _RESULT["kv_code_dtype"] = str(np.dtype(kvsess.cache.k_pool.dtype))

    # the M-invariant oracle holds PER PRECISION: quantized paged decode
    # must match the jitted reference forward at the SAME kv precision
    kslot = kvsess.try_alloc(len(probe), 8)
    kfirst, klogits = kvsess.prefill(kslot, probe)
    np.testing.assert_array_equal(
        klogits, np.asarray(serve_model.reference_last_logits(
            kvsess.params, probe, cfg, sconf.page_size, exact=True,
            kv_quant=kvq)))
    kseq = list(probe) + [kfirst]
    for _ in range(4):
        ktoks, klogs = kvsess.step()
        np.testing.assert_array_equal(
            klogs[kslot], np.asarray(serve_model.reference_last_logits(
                kvsess.params, kseq, cfg, sconf.page_size, exact=True,
                kv_quant=kvq)))
        kseq.append(ktoks[kslot])
    kvsess.release(kslot)
    _RESULT["bitexact_kv_quant"] = True

    # logit drift vs the f32 cache, teacher-forced (same bound shape as
    # the weight A/B: int8 rows carry more mantissa than e4m3)
    kv_drift = 0.0
    bslot = sess.try_alloc(len(probe), 8)
    kslot = kvsess.try_alloc(len(probe), 8)
    _, blog = sess.prefill(bslot, probe)
    _, klog = kvsess.prefill(kslot, probe)
    kv_drift = max(kv_drift, float(np.max(np.abs(klog - blog))))
    for _ in range(6):
        kvsess._slot_tokens[kslot] = sess._slot_tokens[bslot]
        btoks, blogs = sess.step()
        ktoks, klogs = kvsess.step()
        kv_drift = max(kv_drift, float(np.max(np.abs(klogs[kslot]
                                                     - blogs[bslot]))))
    sess.release(bslot)
    kvsess.release(kslot)
    kv_bound = 0.25 if kvq == "int8" else 1.0
    _RESULT["kv_max_logit_drift"] = round(kv_drift, 5)
    _RESULT["kv_logit_drift_bound"] = kv_bound
    assert kv_drift <= kv_bound, \
        "kv %s logit drift %.4f exceeds %.2f" % (kvq, kv_drift, kv_bound)

    # slot capacity at a FIXED pool-byte budget: a page's rows shrink
    # from 4-byte floats to 1-byte codes plus one f32 scale per row, so
    # the same byte budget holds ~(4·H·D)/(H·D+4) times the pages —
    # multiplicative atop oversubscription's admission-by-need
    head_dim = cfg.d_model // cfg.num_heads
    f32_page = PagedKVCache.page_bytes(cfg.num_layers, cfg.num_heads,
                                       head_dim, sconf.page_size)
    q_page = PagedKVCache.page_bytes(cfg.num_layers, cfg.num_heads,
                                     head_dim, sconf.page_size,
                                     kv_quant=kvq)
    _RESULT["kv_page_bytes_f32"] = f32_page
    _RESULT["kv_page_bytes_quant"] = q_page
    _RESULT["kv_capacity_multiplier"] = round(f32_page / q_page, 2)
    budget_pages = 64
    _RESULT["kv_pages_at_budget_f32"] = budget_pages
    _RESULT["kv_pages_at_budget_quant"] = (budget_pages * f32_page) // q_page
    assert _RESULT["kv_capacity_multiplier"] >= 3.0, \
        "kv capacity multiplier %.2f below 3x" \
        % _RESULT["kv_capacity_multiplier"]

    # throughput: quantize-on-append and in-kernel dequant must stay
    # inside the one decode executable.  Recorded, not barred — on CPU
    # the per-block dequant is exposed arithmetic next to tiny matmuls;
    # on bandwidth-bound accelerators the 4x-smaller KV reads win.
    kv_tps = 0.0
    for _ in range(3):
        base_tps = max(base_tps, _decode_tps(sess, ab_steps))
        kv_tps = max(kv_tps, _decode_tps(kvsess, ab_steps))
    _RESULT["decode_tokens_per_sec_kv_quant"] = round(kv_tps, 1)
    _RESULT["kv_quant_speedup"] = round(kv_tps / max(base_tps, 1e-9), 3)
    kv_guards = {
        name: snap for name, snap in kvsess.guard_report().items()
        if snap.get("traces", 0) > 1 or snap.get("signatures", 0) > 1}
    assert not kv_guards, "kv-quant executables retraced: %r" % (kv_guards,)

    # -- prefix caching A/B ----------------------------------------------
    # Prefix-heavy trace: every prompt opens with the same 96-token
    # system preamble (6 full pages at page_size 16) and a 16-token
    # per-request suffix.  The two sessions compile the SAME executable
    # set; only prefix_pages flips.  On a hit the preamble's pages are
    # mapped read-only and prefill runs just the suffix through the
    # 32-bucket instead of the whole prompt through the 112-bucket —
    # the TTFT delta is that compute, measured.
    pfx_conf = _dc.replace(sconf, slots=4, buckets=(32, 112), max_new=4)
    pfx_off = serve.InferenceSession(params, num_heads=cfg.num_heads,
                                     config=pfx_conf)
    pfx_on = serve.InferenceSession(
        params, num_heads=cfg.num_heads,
        config=_dc.replace(pfx_conf, prefix_pages=-1))
    assert len(pfx_on.executables) == len(pfx_conf.buckets) + 1
    assert len(pfx_off.executables) == len(pfx_conf.buckets) + 1
    rs = np.random.RandomState(9)
    preamble = rs.randint(1, 127, size=96).tolist()
    pfx_trace = _poisson_trace(8, mean_gap_s=0.002, prompt_lens=(16,),
                               max_new=4, seed=5)
    for spec in pfx_trace:
        spec["prompt"] = preamble + spec["prompt"]
    # interleaved best-of-3 (as in the quant A/B): each pass replays the
    # identical trace; the on-session's published preamble pages persist
    # across passes, so from the first pass's second request onward
    # every admission is a hit
    pfx_p50 = {"off": float("inf"), "on": float("inf")}
    pfx_p99 = {"off": float("inf"), "on": float("inf")}
    pfx_streams = {}
    for _ in range(3):
        for tag, psess in (("off", pfx_off), ("on", pfx_on)):
            reqs = [serve.Request(**spec) for spec in pfx_trace]
            done, makespan = serve.Scheduler(
                psess, policy="continuous").run(reqs)
            summary = serve.summarize(done, makespan)
            assert summary["failed"] == 0
            pfx_p50[tag] = min(pfx_p50[tag], summary["ttft_p50_s"])
            pfx_p99[tag] = min(pfx_p99[tag], summary["ttft_p99_s"])
            pfx_streams[tag] = {r.rid: list(r.tokens) for r in done}
    stats = pfx_on.cache.prefix_stats
    _RESULT["prefix_hit_rate"] = round(
        stats["hits"] / max(stats["lookups"], 1), 3)
    _RESULT["prefix_prefill_tokens_saved"] = stats["hit_tokens"]
    _RESULT["prefix_ttft_p50_off_s"] = round(pfx_p50["off"], 5)
    _RESULT["prefix_ttft_p50_on_s"] = round(pfx_p50["on"], 5)
    _RESULT["prefix_ttft_p99_off_s"] = round(pfx_p99["off"], 5)
    _RESULT["prefix_ttft_p99_on_s"] = round(pfx_p99["on"], 5)
    _RESULT["prefix_ttft_reduction"] = round(
        1.0 - pfx_p50["on"] / max(pfx_p50["off"], 1e-9), 3)
    # acceptance: hits must MEASURABLY cut TTFT, and the cache may
    # change only the cost of a stream, never its content
    assert _RESULT["prefix_hit_rate"] > 0.5
    assert _RESULT["prefix_prefill_tokens_saved"] > 0
    assert _RESULT["prefix_ttft_reduction"] > 0, \
        "prefix hits did not reduce TTFT (p50 on %.5fs vs off %.5fs)" \
        % (pfx_p50["on"], pfx_p50["off"])
    _RESULT["bitexact_prefix"] = pfx_streams["on"] == pfx_streams["off"]
    assert _RESULT["bitexact_prefix"], "prefix-cache hits drifted"
    assert pfx_on.fallback_count() == 0

    # -- oversubscription A/B at an equal undersized pool ----------------
    # 7-page pool, 16-token prompts decoding 16 tokens (2 pages at
    # rest).  Reservation admission can hold at most 3 requests in
    # flight; oversubscription admits by current need (1 page), fills
    # all 6 slots, and pays with watermark preemption + deterministic
    # re-prefill when growth drains the pool.
    ovs_conf = _dc.replace(sconf, slots=6, buckets=(16, 32), max_new=16,
                           num_pages=7)
    ovs_burst = [dict(rid=i,
                      prompt=np.random.RandomState(20 + i).randint(
                          1, 127, size=16).tolist(),
                      max_new=16, arrival_s=0.0) for i in range(12)]
    ovs_streams, ovs_peak = {}, {}
    for tag, oconf in (("reserved", ovs_conf),
                       ("oversub", _dc.replace(ovs_conf, oversub=True,
                                               watermark=1))):
        osess = serve.InferenceSession(params, num_heads=cfg.num_heads,
                                       config=oconf)
        assert len(osess.executables) == len(oconf.buckets) + 1
        sched = serve.Scheduler(osess, policy="continuous")
        done, makespan = sched.run(
            [serve.Request(**spec) for spec in ovs_burst])
        summary = serve.summarize(done, makespan)
        assert summary["failed"] == 0, "%s: %d requests failed" \
            % (tag, summary["failed"])
        ovs_streams[tag] = {r.rid: list(r.tokens) for r in done}
        ovs_peak[tag] = sched.stats["peak_active"]
        _RESULT["oversub_%s_peak_active" % tag] = sched.stats["peak_active"]
        _RESULT["oversub_%s_tokens_per_sec" % tag] = round(
            summary["tokens_per_sec"], 1)
        if tag == "oversub":
            _RESULT["oversub_preemptions"] = sched.stats["preemptions"]
            _RESULT["oversub_resumes"] = sched.stats["resumes"]
            assert sched.stats["preemptions"] > 0
            assert osess.fallback_count() == 0
    # acceptance: more requests in flight at the same pool size, with
    # bit-identical streams — oversubscription changes capacity only
    assert ovs_peak["oversub"] > ovs_peak["reserved"], \
        "oversub peak %d not above reservation peak %d" \
        % (ovs_peak["oversub"], ovs_peak["reserved"])
    _RESULT["bitexact_oversub"] = (ovs_streams["oversub"]
                                   == ovs_streams["reserved"])
    assert _RESULT["bitexact_oversub"], "preempt-and-recompute drifted"

    # -- closed-loop goodput under a TTFT SLO ----------------------------
    # The scheduler's followup hook spawns one replacement request per
    # completion, holding concurrency at the slot count instead of
    # replaying an open-loop trace; the session's TTFT budget drives
    # can-still-meet-first admission and summarize() reports goodput.
    slo_ms = 250.0
    slo_sess = serve.InferenceSession(
        params, num_heads=cfg.num_heads,
        config=_dc.replace(sconf, slots=4, max_new=8, ttft_slo_ms=slo_ms))
    cl_total = max(n_requests, 12)
    cl_rs = np.random.RandomState(13)
    cl_issued = {"n": 0}

    def _cl_request(now_s):
        cl_issued["n"] += 1
        plen = int(cl_rs.choice((9, 14, 23)))
        return serve.Request(rid=2000 + cl_issued["n"],
                             prompt=cl_rs.randint(1, 127,
                                                  size=plen).tolist(),
                             max_new=8, arrival_s=now_s)

    def _cl_followup(req, now_s):
        return _cl_request(now_s) if cl_issued["n"] < cl_total else None

    seeds = [_cl_request(0.0) for _ in range(4)]
    done, makespan = serve.Scheduler(slo_sess, policy="continuous").run(
        seeds, followup=_cl_followup)
    summary = serve.summarize(done, makespan, ttft_slo_ms=slo_ms)
    assert summary["failed"] == 0
    assert summary["completed"] == cl_total
    assert summary["goodput_rps"] > 0
    _RESULT["closed_loop_requests"] = summary["completed"]
    _RESULT["closed_loop_ttft_slo_ms"] = slo_ms
    _RESULT["closed_loop_goodput_rps"] = round(summary["goodput_rps"], 2)
    _RESULT["closed_loop_slo_attainment"] = round(
        summary["slo_attainment"], 3)
    _RESULT["closed_loop_ttft_p50_s"] = round(summary["ttft_p50_s"], 5)
    _RESULT["closed_loop_ttft_p99_s"] = round(summary["ttft_p99_s"], 5)
    _RESULT["closed_loop_tokens_per_sec"] = round(
        summary["tokens_per_sec"], 1)

    # -- replicated-serving chaos soak -----------------------------------
    # Three identical replicas (replica 0 IS the main session) behind
    # the ReplicaSet dispatcher; one replica is chaos-killed mid-traffic
    # and stays dead (huge rejoin backoff), so the survivors absorb its
    # in-flight work through the park/resume failover path.  Acceptance,
    # asserted here and recorded in the JSON: zero lost requests,
    # completed streams bit-identical to the fault-free baseline run,
    # shed requests typed and accounted, goodput >= 60% of the baseline
    # (proportional to the capacity that survived), and the per-replica
    # executable count frozen across death + failover.
    from mxnet_tpu.testing import faults as _faults

    soak_sessions = [sess] + [
        serve.InferenceSession(params, num_heads=cfg.num_heads,
                               config=sconf) for _ in range(2)]
    soak_n = max(3 * n_requests // 2, 24)
    soak_trace = _poisson_trace(soak_n, mean_gap_s=0.002,
                                prompt_lens=(9, 14), max_new=8, seed=11)

    def _soak_run():
        rs_set = serve.ReplicaSet(sessions=soak_sessions,
                                  rejoin_backoff_s=1e9)
        done, makespan = rs_set.run(
            [serve.Request(**spec) for spec in soak_trace])
        return rs_set, done, makespan, serve.summarize(done, makespan)

    # fault-free baseline: the goodput bar's denominator and the
    # bit-exactness oracle
    _, base_done, base_makespan, base_sum = _soak_run()
    assert base_sum["failed"] == 0 and base_sum["completed"] == soak_n
    soak_oracle = {r.rid: list(r.tokens) for r in base_done}
    base_rps = base_sum["completed"] / max(base_makespan, 1e-9)

    import os as _os
    _os.environ["MXNET_FAULT_INJECT"] = "serve_replica_kill:kill:after=16"
    _faults.reset()
    try:
        rs_set, done, makespan, soak_sum = _soak_run()
    finally:
        del _os.environ["MXNET_FAULT_INJECT"]
        _faults.reset()
    _RESULT["soak_replicas"] = 3
    _RESULT["soak_requests"] = soak_n
    _RESULT["soak_deaths"] = rs_set.counters["deaths"]
    _RESULT["soak_failover_requests"] = rs_set.counters["failover_requests"]
    _RESULT["soak_resumes"] = soak_sum["resumes"]
    _RESULT["soak_shed"] = soak_sum["shed"]
    _RESULT["soak_completed"] = soak_sum["completed"]
    assert rs_set.counters["deaths"] == 1
    # zero lost: every request either completed or was shed TYPED —
    # nothing vanished with the dead replica
    _RESULT["soak_zero_lost"] = (
        soak_sum["completed"] + soak_sum["shed"] == soak_n
        and soak_sum["faulted"] == 0)
    assert _RESULT["soak_zero_lost"], \
        "soak lost requests: %r" % {k: soak_sum[k] for k in
                                    ("completed", "shed", "faulted")}
    assert all(("ServeOverloaded" in r.error) for r in done if r.failed)
    # completed streams bit-identical to the never-failed baseline
    _RESULT["soak_bitexact"] = all(
        soak_oracle[r.rid] == r.tokens for r in done if not r.failed)
    assert _RESULT["soak_bitexact"], "failover streams drifted"
    # goodput degrades no worse than the capacity lost: one of three
    # replicas died mid-run, so >= 60% of baseline must survive
    soak_rps = soak_sum["completed"] / max(makespan, 1e-9)
    _RESULT["soak_baseline_rps"] = round(base_rps, 2)
    _RESULT["soak_chaos_rps"] = round(soak_rps, 2)
    _RESULT["soak_goodput_ratio"] = round(soak_rps / max(base_rps, 1e-9), 3)
    assert _RESULT["soak_goodput_ratio"] >= 0.6, \
        "soak goodput %.2f below 60%% of baseline" \
        % _RESULT["soak_goodput_ratio"]
    # executables stay frozen per replica across death + failover
    _RESULT["soak_executables_per_replica"] = rs_set.executables_per_replica()
    assert rs_set.executables_per_replica() \
        == [len(sconf.buckets) + 1] * 3, "soak minted executables"
    assert all(s.fallback_count() == 0 for s in soak_sessions)
    _RESULT["soak_incident"] = rs_set.incident_path

    # deterministic overload probe: a 2-deep admission queue under the
    # same burst must shed typed, with the accounting closed
    rs_over = serve.ReplicaSet(sessions=soak_sessions[1:], queue_cap=2)
    odone, omakespan = rs_over.run(
        [serve.Request(**spec) for spec in soak_trace])
    over_sum = serve.summarize(odone, omakespan)
    _RESULT["soak_overload_shed"] = over_sum["shed"]
    assert over_sum["shed"] > 0 and over_sum["faulted"] == 0
    assert over_sum["completed"] + over_sum["shed"] == soak_n
    assert all(r.shed and "ServeOverloaded" in r.error
               for r in odone if r.failed)
    assert over_sum["shed"] == rs_over.counters["shed"]

    # -- network-edge soak: the same chaos, now over real sockets --------
    # A streaming asyncio Gateway fronts three fresh replicas; threaded
    # socket clients replay the Poisson trace closed-loop (every 5th
    # client crashes mid-stream with an RST) while one replica is
    # chaos-killed mid-traffic.  Acceptance, asserted: zero lost
    # requests (every client reached a typed terminal outcome),
    # completed streams byte-identical to the in-process oracle,
    # cancellation returned every replica to its cold-state snapshot,
    # the per-replica executable count frozen, and the closing
    # SIGTERM-style drain completed clean.
    import threading as _threading

    for s in soak_sessions:
        s.reset_cold()
    gw_snap = [s.state_report() for s in soak_sessions]
    rs_gw = serve.ReplicaSet(sessions=soak_sessions, rejoin_backoff_s=1e9)
    gw = serve.Gateway(rs_gw, port=0).start()
    gw_out = {}
    gw_drops = set(range(2, soak_n, 5))
    gw_threads = [
        _threading.Thread(target=_gw_client,
                          args=(gw.port, spec, spec["rid"] in gw_drops,
                                gw_out))
        for spec in soak_trace]
    _os.environ["MXNET_FAULT_INJECT"] = "serve_replica_kill:kill:after=16"
    _faults.reset()
    gw_t0 = time.perf_counter()
    try:
        for t in gw_threads:
            t.start()
        for t in gw_threads:
            t.join(timeout=300)
    finally:
        del _os.environ["MXNET_FAULT_INJECT"]
        _faults.reset()
    gw_wall = time.perf_counter() - gw_t0
    _RESULT["gw_drain_clean"] = bool(gw.drain(wait=True))
    gw.stop()
    assert not any(t.is_alive() for t in gw_threads), "socket client hung"
    outcomes = [rec["outcome"] for rec in gw_out.values()]
    _RESULT["gw_requests"] = soak_n
    _RESULT["gw_completed"] = outcomes.count("completed")
    _RESULT["gw_disconnects"] = outcomes.count("disconnected")
    _RESULT["gw_shed_429"] = outcomes.count("shed")
    _RESULT["gw_deaths"] = rs_gw.counters["deaths"]
    assert rs_gw.counters["deaths"] == 1
    # zero lost: nothing timed out, errored untyped, or vanished with
    # the dead replica or the crashed clients
    _RESULT["gw_zero_lost"] = (
        len(gw_out) == soak_n
        and all(o in ("completed", "disconnected", "shed")
                for o in outcomes))
    assert _RESULT["gw_zero_lost"], \
        "gateway soak lost requests: %r" % sorted(set(outcomes))
    assert _RESULT["gw_completed"] \
        >= soak_n - len(gw_drops) - _RESULT["gw_shed_429"]
    # every completed stream byte-identical to the in-process oracle
    _RESULT["gw_bitexact"] = all(
        rec["tokens"] == soak_oracle[rid]
        for rid, rec in gw_out.items() if rec["outcome"] == "completed")
    assert _RESULT["gw_bitexact"], "gateway streams drifted from oracle"
    # the drain was clean: no stream needed a force-cancel
    assert _RESULT["gw_drain_clean"], "gateway drain force-cancelled"
    assert gw.counters["force_cancelled"] == 0
    # crashed clients + chaos kill freed everything: each replica is
    # byte-for-byte back at its cold snapshot
    assert [s.state_report() for s in soak_sessions] == gw_snap, \
        "gateway soak leaked serving state"
    assert rs_gw.executables_per_replica() \
        == [len(sconf.buckets) + 1] * 3, "gateway soak minted executables"
    gw_rps = _RESULT["gw_completed"] / max(gw_wall, 1e-9)
    gw_ttfts = sorted(rec["ttft_s"] for rec in gw_out.values()
                      if rec["ttft_s"] is not None)
    gw_ttft_p50 = gw_ttfts[len(gw_ttfts) // 2]
    _RESULT["gw_goodput_rps"] = round(gw_rps, 2)
    _RESULT["gw_goodput_ratio"] = round(gw_rps / max(base_rps, 1e-9), 3)
    _RESULT["gw_ttft_p50_s"] = round(gw_ttft_p50, 5)
    # the wire tax: socket TTFT p50 minus the in-process baseline's
    _RESULT["gw_ttft_p50_delta_s"] = round(
        gw_ttft_p50 - base_sum["ttft_p50_s"], 5)
    assert _RESULT["gw_goodput_ratio"] >= 0.2, \
        "gateway goodput %.2f below 20%% of in-process baseline" \
        % _RESULT["gw_goodput_ratio"]
    _RESULT["gw_counters"] = dict(gw.counters)

    # -- hybrid long-context A/B: O(1) per-slot serving memory -----------
    # Windowed-ring + SSM stacks against full attention at a FIXED
    # pool-byte budget.  Two acceptance bars: the hybrid stack reserves
    # no pages (admission is slot-bounded), so peak concurrency at the
    # same pool bytes must be >= 2x; and its per-slot state is constant
    # in context length, so per-token decode latency must stay flat as
    # the context jumps 4k -> 32k (the full-attention pool could not
    # even HOLD those contexts).
    hyb_window = 16
    ab_max_new = 112  # 144-token requests: context >> window
    long_cfg = serve.ModelConfig(vocab_size=128, num_layers=2,
                                 d_model=64, num_heads=2, max_len=33024)
    long_params = serve_model.init_params(long_cfg, seed=0)
    ab_base = _dc.replace(sconf, slots=8, buckets=(32,),
                          max_new=ab_max_new)
    hyb_conf = _dc.replace(ab_base, num_pages=1, layers="window,ssm",
                           window=hyb_window)
    hyb_ab = serve.InferenceSession(long_params, num_heads=2,
                                    config=hyb_conf)
    # executable count frozen: hybrid changes executable ARGUMENTS
    # (ring/state pools), never the executable set
    assert len(hyb_ab.executables) == len(hyb_conf.buckets) + 1
    # the full-attention side gets the hybrid footprint as its page
    # budget — the fixed-pool-bytes framing of the capacity claim
    hyb_bytes = hyb_ab.cache.pool_bytes()
    ab_page = PagedKVCache.page_bytes(
        long_cfg.num_layers, long_cfg.num_heads,
        long_cfg.d_model // long_cfg.num_heads, sconf.page_size)
    full_conf = _dc.replace(ab_base, num_pages=max(hyb_bytes // ab_page,
                                                   1))
    full_ab = serve.InferenceSession(long_params, num_heads=2,
                                     config=full_conf)
    _RESULT["window_pool_bytes_full"] = full_ab.cache.pool_bytes()
    _RESULT["window_pool_bytes_hybrid"] = hyb_bytes
    assert hyb_bytes <= _RESULT["window_pool_bytes_full"] + ab_page, \
        "hybrid exceeded the fixed byte budget"

    ab_rs = np.random.RandomState(17)
    ab_peak, ab_tps = {}, {}
    for tag, ab_sess in (("full", full_ab), ("hybrid", hyb_ab)):
        reqs = [serve.Request(rid=i,
                              prompt=ab_rs.randint(1, 127,
                                                   size=32).tolist(),
                              max_new=ab_max_new, arrival_s=0.0)
                for i in range(8)]
        sched = serve.Scheduler(ab_sess, policy="continuous")
        done, makespan = sched.run(reqs)
        summary = serve.summarize(done, makespan)
        assert summary["failed"] == 0, "%s A/B failed requests" % tag
        ab_peak[tag] = sched.stats["peak_active"]
        ab_tps[tag] = round(summary["tokens_per_sec"], 1)
    _RESULT["window_peak_active_full"] = ab_peak["full"]
    _RESULT["window_peak_active_hybrid"] = ab_peak["hybrid"]
    _RESULT["window_goodput_full_tps"] = ab_tps["full"]
    _RESULT["window_goodput_hybrid_tps"] = ab_tps["hybrid"]
    _RESULT["window_capacity_ratio"] = round(
        ab_peak["hybrid"] / max(ab_peak["full"], 1), 2)
    assert _RESULT["window_capacity_ratio"] >= 2.0, \
        "hybrid capacity %.2fx below the 2x acceptance bar" \
        % _RESULT["window_capacity_ratio"]

    # flat-latency probe: pin the slot's context length artificially
    # (the executables read lengths as data; a no-full-layer stack has
    # no page tables to outgrow) and time steady-state decode steps at
    # 4k and 32k.  O(context) attention would be ~8x slower at 32k;
    # the O(1) hybrid step must stay within noise.
    probe_slot = hyb_ab.try_alloc(16, 16)
    hyb_ab.prefill(probe_slot, list(ab_rs.randint(1, 127, size=16)))

    def _pinned_step_ms(ctx_len, steps=24):
        best = float("inf")
        for _ in range(3):
            hyb_ab.cache.lengths[probe_slot] = ctx_len
            hyb_ab.step()  # warm this context length
            t0 = time.perf_counter()
            for _ in range(steps):
                hyb_ab.cache.lengths[probe_slot] = ctx_len
                hyb_ab.step()
            best = min(best, (time.perf_counter() - t0) / steps)
        return best * 1e3

    ms_4k = _pinned_step_ms(4096)
    ms_32k = _pinned_step_ms(32640)
    hyb_ab.release(probe_slot)
    _RESULT["window_decode_ms_4k"] = round(ms_4k, 4)
    _RESULT["window_decode_ms_32k"] = round(ms_32k, 4)
    _RESULT["window_latency_ratio_32k_over_4k"] = round(
        ms_32k / max(ms_4k, 1e-9), 3)
    assert _RESULT["window_latency_ratio_32k_over_4k"] <= 1.5, \
        "hybrid decode latency grew %.2fx from 4k to 32k context" \
        % _RESULT["window_latency_ratio_32k_over_4k"]

    # -- acceptance probe 3: no per-request recompiles -------------------
    guards = sess.guard_report()
    _RESULT["recompiles"] = {
        name: snap for name, snap in guards.items()
        if snap.get("traces", 0) > 1 or snap.get("signatures", 0) > 1}
    assert not _RESULT["recompiles"], \
        "serving executables retraced: %r" % (_RESULT["recompiles"],)
    assert len(sess.executables) == len(sconf.buckets) + 1
    _RESULT["dispatch_fallbacks"] = sess.fallback_count()

    # -- satellite: compile-report artifact for the serving set ----------
    try:
        _RESULT["compile_report"] = compile_cache.write_artifact()
    except Exception as exc:
        _RESULT["compile_report_error"] = str(exc)[:200]
    return dict(_RESULT)


def main():
    # watchdog + budget armed before measure()'s jax imports: a hung
    # backend init still yields valid partial JSON + exit 0
    seconds = None
    for i, a in enumerate(sys.argv):
        if a == "--watchdog" and i + 1 < len(sys.argv):
            seconds = float(sys.argv[i + 1])
        elif a.startswith("--watchdog="):
            seconds = float(a.split("=", 1)[1])
    bench_util.arm_watchdog(_RESULT, seconds=seconds)
    bench_util.arm_budget(_RESULT)
    result = measure()
    result.update(bench_util.compile_summary())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
