#!/usr/bin/env python
"""Secondary benchmark: decoder-only transformer LM training MFU on one
TPU chip.

`bench.py` (the driver metric) measures ResNet-50 — which at 224px is
HBM-bandwidth-bound on this hardware generation (see README).  This
benchmark exists to show the framework's compute ceiling on an MXU-bound
workload: a GPT-style model whose FLOPs sit in large matmuls.

Prints ONE JSON line with tokens/sec and %MFU.

Usage: bench_transformer.py [--small|--deep|--moe] [--batch=N]
"""
import json
import sys
import time

sys.path.insert(0, ".")

import bench_util

PEAK_BF16 = {"TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
             "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12}

# phase-by-phase partial result for the MXNET_BENCH_BUDGET_S emitter
_RESULT = {"metric": "transformer_lm_tokens_per_sec_per_chip"}


def measure(argv=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.fused import TrainStep
    from mxnet_tpu.models import transformer

    argv = sys.argv if argv is None else argv
    small = "--small" in argv
    if small:
        cfg = dict(vocab_size=8192, num_layers=4, d_model=256,
                   num_heads=4, seq_len=256)
    elif "--deep" in argv:
        cfg = dict(vocab_size=32768, num_layers=16, d_model=1024,
                   num_heads=16, seq_len=1024)
    elif "--moe" in argv:
        # routed top-2 MoE: 8 experts of d_ff=1024 per block = 2x the
        # total FFN parameters of the dense d1024 config (d_ff=4096)
        # with only a 2048-wide active path per token (top-2) — the
        # capacity/compute decoupling MoE buys.  Single-chip routed
        # dispatch, no expert mesh.
        cfg = dict(vocab_size=32000, num_layers=8, d_model=1024,
                   num_heads=8, seq_len=1024, d_ff=1024,
                   moe_experts=8, moe_top_k=2)
    else:
        # the MFU-headline config: d2048 keeps every matmul MXU-shaped
        # (measured 65% MFU at batch 8 vs 42% for the 16L-d1024 config)
        cfg = dict(vocab_size=32768, num_layers=8, d_model=2048,
                   num_heads=16, seq_len=1024)
    batch = 2 if small else int(next((a.split("=")[1] for a in argv
        if a.startswith("--batch=")), 8))
    remat = next((a.split("=")[1] for a in argv
                  if a.startswith("--remat=")), None)

    sym = transformer.get_symbol(**cfg)
    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 1e-3,
                                       "momentum": 0.9,
                                       "rescale_grad": 1.0 / batch},
                     compute_dtype="bfloat16", remat=remat)
    shapes = {"data": (batch, cfg["seq_len"]),
              "softmax_label": (batch, cfg["seq_len"])}
    # compile_s measured separately from step_s (and reused from the
    # persistent cache on a repeat run)
    compile_s = bench_util.timed_compile(step, shapes, _RESULT)
    _RESULT["compile_s"] = round(compile_s, 3)
    # attention peak-memory visibility: the compiled step's temp-buffer
    # peak (memory_analysis, the examples/memcost harness) is dominated
    # by attention intermediates at these shapes, so this one number
    # makes the O(T^2) -> O(T*block) flash drop visible per-PR
    try:
        mem = step._aot.memory_analysis()
        _RESULT["attn_peak_bytes"] = int(mem.temp_size_in_bytes)
    except Exception:
        _RESULT["attn_peak_bytes"] = None
    params, aux, states = step.init_state(shapes)
    # optimizer-state residency beside the attention peak: per-replica
    # state bytes plus the per-step fresh-param all-gather volume (0
    # unless the ZeRO sharded update is active — needs a >=2-way mesh)
    mem_rep = step.memory_report(params, states)
    _RESULT["opt_state_bytes"] = int(mem_rep.get("opt_state_bytes") or 0)
    _RESULT["update_gather_bytes"] = int(
        mem_rep.get("update_gather_bytes") or 0)
    # ZeRO-3 residency columns: at-rest per-replica param bytes (1/N
    # when params are sharded at rest) and the total per-step gather
    # traffic (2x the sharded footprint under zero=3: forward bucket
    # gathers + backward re-gathers; the stage-1 trailing gather
    # otherwise)
    _RESULT["params_bytes_at_rest"] = int(
        mem_rep.get("params_bytes_per_replica") or 0)
    _RESULT["gather_bytes_per_step"] = int(
        mem_rep.get("gather_bytes_per_step") or 0)
    rng = jax.random.PRNGKey(0)
    toks = jnp.asarray(
        np.random.RandomState(0).randint(
            0, cfg["vocab_size"], shapes["data"]).astype("float32"))
    batch_dict = {"data": toks, "softmax_label": toks}

    moe = "moe_experts" in cfg
    if moe:
        # analytic count ignores MoE; count the real params.  6*P*tokens
        # is NOT the executed-FLOP count under top-k routing (only k/E
        # of expert FLOPs run), so the MoE row reports tokens/s only.
        p_count = sum(int(np.prod(v.shape)) for v in params.values())
    else:
        p_count = transformer.count_params(**cfg)
    tokens = batch * cfg["seq_len"]
    # analytic train FLOPs (MAC=2): 6*P*tokens for the matmul stack plus
    # the attention score/value terms; skipped for MoE (6*P overcounts
    # top-k-routed expert FLOPs)
    flops_per_step = None if moe else (
        6.0 * p_count * tokens +
        12.0 * cfg["num_layers"] * batch *
        cfg["seq_len"] ** 2 * cfg["d_model"])

    params, aux, states, out = step(params, aux, states, batch_dict, rng)
    float(np.asarray(out[0][0, 0]))  # force compile + completion
    iters = 3 if small else 10
    t0 = time.perf_counter()
    for _ in range(iters):
        params, aux, states, out = step(params, aux, states, batch_dict,
                                        rng)
    float(np.asarray(out[0][0, 0]))
    dt = (time.perf_counter() - t0) / iters

    achieved = None if flops_per_step is None \
        else flops_per_step / dt
    device = jax.devices()[0]
    kind = getattr(device, "device_kind", "unknown")
    peak = next((v for k, v in PEAK_BF16.items() if kind.startswith(k)),
                None)
    _RESULT.update({
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(tokens / dt, 1),
        "unit": "tokens/s",
        "model": "%dL-d%d-T%d%s (%.0fM params)" % (
            cfg["num_layers"], cfg["d_model"], cfg["seq_len"],
            "-MoE-E%d-top%d" % (cfg["moe_experts"], cfg["moe_top_k"])
            if moe else "",
            p_count / 1e6),
        "step_ms": round(dt * 1e3, 2),
        "step_s": round(dt, 4),
        "compile_s": round(compile_s, 3),
        "achieved_tflops": round(achieved / 1e12, 2)
                           if achieved is not None else None,
        "mfu_pct": round(100 * achieved / peak, 2)
                   if peak and achieved is not None else None,
        # 6*P*tokens (matmul stack) + 12*L*B*T^2*d_model (attention
        # score/value contractions, MAC=2) — the honest numerator at
        # long T, where the quadratic term is a double-digit share
        "flops_accounting": None if moe else "6P_tokens+attn_12LBT2D",
        "precision": "bf16+fp32-master",
        # the dtype the 6*P numerator counts over: training weights
        # stay fp32 master (serving may quantize at rest — that shows
        # up in bench_serve.py's quant_* fields, never here)
        "weight_dtype": str(next(iter(params.values())).dtype),
        "device": kind,
    })
    # autotune provenance: which cached knobs (if any) this step was
    # built under — MXNET_AUTOTUNE=1 + a tools/autotune.py record
    try:
        from mxnet_tpu import autotune
        _RESULT["autotune"] = autotune.provenance()
    except ImportError:
        _RESULT["autotune"] = []
    return dict(_RESULT)


def main():
    # watchdog + budget arm before measure()'s jax imports: a hung
    # backend init still yields valid partial JSON + exit 0 (no
    # module-level jax import exists in this file, so arming here is
    # already first-touch)
    bench_util.arm_watchdog(_RESULT)
    bench_util.arm_budget(_RESULT)
    result = measure()
    result.update(bench_util.compile_summary())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
