"""Shared bench-script plumbing: budget + watchdog + compile accounting.

Every bench script prints ONE final JSON line on stdout.  Before this
module existed, a harness timeout (rc 124) killed the process mid-phase
and the artifact parsed as null — rounds 1-5 of BENCH/MULTICHIP all died
that way.  Two timers bound the run from the inside instead:

* ``arm_budget`` — ``MXNET_BENCH_BUDGET_S`` seconds after arming, the
  shared result dict (filled phase by phase by the script) is printed
  as the final stdout line (marked ``"partial": true``) and the process
  exits 0.  Opt-in: no budget env, no timer.
* ``arm_watchdog`` — the always-on wedge guard (default 420 s,
  ``MXNET_BENCH_WATCHDOG`` / ``--watchdog`` to change, 0 disables): if
  the run is still going when it fires — a hung backend init, a stale
  TPU lockfile, a wedged device tunnel — the same partial line is
  emitted and the process exits 0.  Round 5 regressed exactly here:
  the old per-script watchdog imported mxnet_tpu from its timer thread,
  which deadlocks on the interpreter's import lock when the main thread
  is stuck inside ``import jax``, so the harness timeout (rc 124) won
  and the artifact parsed as null.  Both timers now share one emitter
  that touches already-imported modules only.

``compile_summary`` splits compile time out of the measured rates: the
scripts AOT-compile through ``TrainStep.compile``/``Module.fit`` warmup,
so every XLA compile lands in ``mxnet_tpu.profiler.compile_events`` and
the persistent-cache hit/miss counters (see docs/compilation.md).
"""
import json
import os
import sys
import threading


def budget_seconds():
    """The configured bench budget (0 = unbounded)."""
    for key in ("MXTPU_BENCH_BUDGET_S", "MXNET_BENCH_BUDGET_S"):
        raw = os.environ.get(key)
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
    return 0.0


def watchdog_seconds():
    """The wedge-guard timeout (default 420 s; 0 disables).  Sized to
    beat the harness's external timeout: an internally-bounded run
    emits partial JSON and exits 0, an externally-killed one is rc=124
    with nothing on stdout."""
    for key in ("MXTPU_BENCH_WATCHDOG", "MXNET_BENCH_WATCHDOG"):
        raw = os.environ.get(key)
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
    return 420.0


def _emit_and_exit(result, extra):
    """Finalize ``result`` from a timer thread and hard-exit 0.

    MUST NOT import anything: the main thread may be stuck inside
    ``import jax`` holding the import lock, and a blocked emitter is
    exactly the round-5 no-artifact failure.  Compile stats are read
    only when their modules already finished importing."""
    result.update(extra)
    try:
        if "mxnet_tpu.profiler" in sys.modules and \
                "mxnet_tpu.compile_cache" in sys.modules:
            result.update(compile_summary())
    except Exception:
        pass
    print(json.dumps(result), flush=True)
    # stdout is line-buffered under pipes; make sure the line left
    sys.stdout.flush()
    os._exit(0)


def arm_budget(result, seconds=None):
    """Arm the wall-clock budget for this bench process.

    ``result`` is the script's shared phase-by-phase dict; on expiry it
    is finalized with ``partial``/``budget_s`` plus the compile summary,
    printed to stdout as the one JSON line, and the process exits 0 (a
    budgeted run IS a successful run — it reports what finished).
    Returns the armed Timer, or None when no budget is configured."""
    if seconds is None:
        seconds = budget_seconds()
    if seconds <= 0:
        return None
    # mxlint: disable=MX006 — the timer IS the teardown of last
    # resort (it hard-exits the process); joining it would defeat it
    t = threading.Timer(seconds, _emit_and_exit,
                        (result, {"partial": True, "budget_s": seconds}))
    t.daemon = True
    t.start()
    return t


def arm_watchdog(result, seconds=None):
    """Arm the always-on wedge guard (call BEFORE the first jax touch).

    Unlike the opt-in budget, this fires even with no budget configured:
    ``seconds`` (default :func:`watchdog_seconds`) after arming, the
    partial result line is printed and the process exits 0.  Returns the
    Timer, or None when disabled (0)."""
    if seconds is None:
        seconds = watchdog_seconds()
    if seconds <= 0:
        return None
    # mxlint: disable=MX006 — deliberate daemon watchdog, never joined
    t = threading.Timer(
        seconds, _emit_and_exit,
        (result, {"partial": True, "watchdog_timeout_sec": seconds}))
    t.daemon = True
    t.start()
    return t


def compile_summary():
    """Process-wide compile accounting for the final result line:
    total ``compile_s``, persistent-cache counters, and any callable
    the recompile guard saw trace more than once."""
    out = {}
    try:
        from mxnet_tpu import compile_cache, profiler

        out["compile_s"] = round(profiler.total_compile_s(), 3)
        cs = compile_cache.cache_stats()
        out["compile_cache"] = {
            k: cs[k] for k in ("enabled", "hits", "misses", "entries",
                               "bytes")}
        retraced = {name: snap["traces"]
                    for name, snap in compile_cache.registry.report().items()
                    if snap["traces"] > 1}
        if retraced:
            out["recompiles"] = retraced
    except Exception as e:  # accounting must never sink the benchmark
        out["compile_stats_error"] = str(e)[:160]
    return out


def timed_compile(step, shapes, result=None, key="compile_s"):
    """AOT-compile ``step`` for ``shapes`` and return the compile wall
    seconds (also accumulated into ``result[key]`` when given).  Falls
    back to 0.0 when the step has no AOT form — the caller's first
    dispatch then absorbs the (lazy) compile as before."""
    try:
        stats = step.compile(shapes)
        dt = float(stats["duration_s"])
    except Exception:
        return 0.0
    if result is not None:
        result[key] = round(result.get(key, 0.0) + dt, 3)
    return dt
