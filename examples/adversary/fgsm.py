#!/usr/bin/env python
"""Adversarial examples by FGSM (reference ``example/adversary/``):
train a classifier, then perturb inputs along the SIGN of the loss
gradient w.r.t. the INPUT — accuracy must collapse under an epsilon
that leaves the images visually unchanged, and recover when the
perturbation is random instead of adversarial.

Exercises ``Module.bind(inputs_need_grad=True)`` + ``get_input_grads``
— the executor's data-gradient path.

    python examples/adversary/fgsm.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def synth(n, rs):
    """4-class blobs in 16-d space with margin."""
    centers = rs.randn(4, 16).astype("float32") * 1.0
    y = rs.randint(0, 4, n).astype("float32")
    X = centers[y.astype(int)] + 0.4 * rs.randn(n, 16).astype("float32")
    return X, y


def accuracy(mod, X, y):
    mod.forward(mx.io.DataBatch([mx.nd.array(X)], [mx.nd.array(y)]),
                is_train=False)
    pred = mod.get_outputs()[0].asnumpy()
    return float((pred.argmax(1) == y).mean())


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X, y = synth(args.num_examples, rs)
    it = mx.io.NDArrayIter(X, y, batch_size=args.num_examples)
    mod = mx.mod.Module(get_symbol(), context=mx.tpu(0))
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label, inputs_need_grad=True)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    for _ in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()

    clean_acc = accuracy(mod, X, y)

    # FGSM: x_adv = x + eps * sign(dL/dx)
    mod.forward(mx.io.DataBatch([mx.nd.array(X)], [mx.nd.array(y)]),
                is_train=True)
    mod.backward()
    gx = mod.get_input_grads()[0].asnumpy()
    X_adv = X + args.eps * np.sign(gx)
    adv_acc = accuracy(mod, X_adv, y)

    # control: the same budget of RANDOM-sign noise barely hurts
    X_rand = X + args.eps * np.sign(rs.randn(*X.shape)).astype("float32")
    rand_acc = accuracy(mod, X_rand, y)

    print("clean acc %.3f | FGSM(eps=%.2f) acc %.3f | random-sign "
          "acc %.3f" % (clean_acc, args.eps, adv_acc, rand_acc))
    return clean_acc, adv_acc, rand_acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=40)
    p.add_argument("--eps", type=float, default=0.8)
    main(p.parse_args())
