#!/usr/bin/env python
"""Deep autoencoder (reference ``example/autoencoder/``: stacked
encoder-decoder trained on reconstruction loss, the unsupervised
pattern).  Tied task: 16x16 images that live on a 3-dim latent
manifold; the 3-unit bottleneck must reconstruct far better than the
best LINEAR rank-3 control (PCA with the same latent budget), proving
the nonlinear code learned the manifold.

    python examples/autoencoder/autoencoder.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(bottleneck=3):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="enc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=bottleneck, name="enc2")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="dec1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=256, name="dec2")
    # reconstruction target = the input itself (label slot)
    return mx.sym.LinearRegressionOutput(h, name="recon")


def synth(n, rs):
    """Images = blob at (cx, cy) with radius r — a 3-dim manifold."""
    yy, xx = np.mgrid[0:16, 0:16]
    imgs = np.empty((n, 256), "float32")
    for i in range(n):
        cy, cx = rs.uniform(4, 12, 2)
        r = rs.uniform(2, 5)
        imgs[i] = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2)
                           / (r * r))).ravel()
    return imgs


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X = synth(args.num_examples, rs)
    it = mx.io.NDArrayIter({"data": X}, {"recon_label": X},
                           batch_size=64)
    mod = mx.mod.Module(get_symbol(), label_names=("recon_label",),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.MSE())

    # reconstruction error vs the best rank-3 LINEAR baseline (PCA)
    mod.forward(mx.io.DataBatch([mx.nd.array(X)], [mx.nd.array(X)]),
                is_train=False)
    rec = mod.get_outputs()[0].asnumpy()
    ae_mse = float(((rec - X) ** 2).mean())
    Xc = X - X.mean(0)
    _u, s, vt = np.linalg.svd(Xc, full_matrices=False)
    pca3 = Xc @ vt[:3].T @ vt[:3] + X.mean(0)
    pca_mse = float(((pca3 - X) ** 2).mean())
    print("AE(3) mse %.5f | PCA(3) mse %.5f | ratio %.2f"
          % (ae_mse, pca_mse, ae_mse / pca_mse))
    return ae_mse, pca_mse


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=1024)
    p.add_argument("--num-epochs", type=int, default=30)
    main(p.parse_args())
