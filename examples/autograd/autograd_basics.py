#!/usr/bin/env python
"""Imperative autograd walkthrough (reference ``example/autograd/``):
tape recording, higher-level ``grad``, and a custom training loop without
Module/Gluon.

    python examples/autograd/autograd_basics.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def main():
    # 1. basic tape
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x * x)
    y.backward()
    print("d(sum x^2)/dx =", x.grad.asnumpy())  # 2x

    # 2. the old contrib surface
    from mxnet_tpu.contrib import autograd as cag

    @cag.grad_and_loss
    def loss_fn(w):
        return nd.sum(nd.exp(w))

    grads, loss = loss_fn(nd.array([0.0, 1.0]))
    print("contrib grad:", grads[0].asnumpy())

    # 3. linear regression by hand
    rs = np.random.RandomState(0)
    xs = nd.array(rs.rand(128, 4).astype("float32"))
    true_w = nd.array(rs.rand(4, 1).astype("float32"))
    ys = nd.dot(xs, true_w)
    w = nd.zeros((4, 1))
    w.attach_grad()
    for step in range(200):
        with autograd.record():
            err = nd.dot(xs, w) - ys
            loss = nd.sum(err * err) / 128.0
        loss.backward()
        w[:] = w - 0.5 * w.grad
    print("recovered |w - w*|:",
          float(nd.max(nd.abs(w - true_w)).asnumpy()))


if __name__ == "__main__":
    main()
