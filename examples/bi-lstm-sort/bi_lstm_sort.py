#!/usr/bin/env python
"""Bidirectional-LSTM sequence sorting (reference
``example/bi-lstm-sort/``: read a sequence of tokens, emit the same
tokens sorted — the classic seq-labeling task showing a BiLSTM sees
the whole sequence at every output position).

Uses the rnn toolkit's ``BidirectionalCell`` over ``LSTMCell``s with
``unroll``, per-position softmax — every output position must name the
k-th smallest input token.

    python examples/bi-lstm-sort/bi_lstm_sort.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(vocab, seq_len, num_hidden):
    data = mx.sym.Variable("data")          # (N, T) token ids
    label = mx.sym.Variable("softmax_label")  # (N, T) sorted ids
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_hidden,
                             name="embed")
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="l_"),
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="r_"))
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             layout="NTC")
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="cls")
    label_f = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_f, name="softmax",
                                normalization="batch")


def synth(n, vocab, seq_len, rs):
    data = rs.randint(0, vocab, (n, seq_len)).astype("float32")
    label = np.sort(data, axis=1).astype("float32")
    return data, label


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    data, label = synth(args.num_examples, args.vocab, args.seq_len, rs)
    it = mx.io.NDArrayIter(data, label, batch_size=args.batch_size)
    mod = mx.mod.Module(get_symbol(args.vocab, args.seq_len,
                                   args.num_hidden), context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=None))
    # per-position accuracy of the sort
    mod.forward(mx.io.DataBatch([mx.nd.array(data)],
                                [mx.nd.array(label)]), is_train=False)
    pred = mod.get_outputs()[0].asnumpy().reshape(
        len(data), args.seq_len, args.vocab)
    acc = float((pred.argmax(-1) == label).mean())
    print("sort accuracy %.4f (per position)" % acc)
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=6)
    p.add_argument("--num-hidden", type=int, default=48)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-examples", type=int, default=2048)
    p.add_argument("--num-epochs", type=int, default=15)
    main(p.parse_args())
