#!/usr/bin/env python
"""CNN for sentence classification (reference
``example/cnn_text_classification/text_cnn.py`` — Kim 2014: embedding,
parallel convolutions with multiple kernel heights over the token
axis, max-over-time pooling, concat, dropout, softmax).

Synthetic task: a sequence is positive iff it contains the trigram
pattern [3, 1, 4] — exactly the local-pattern detection the
multi-width conv + max-over-time architecture exists for.

    python examples/cnn_text_classification/text_cnn.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(vocab, seq_len, embed=32, filters=(3, 4, 5),
               num_filter=16, dropout=0.3):
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                           name="embed")
    x = mx.sym.Reshape(emb, shape=(-1, 1, seq_len, embed))
    pooled = []
    for k in filters:
        c = mx.sym.Convolution(x, num_filter=num_filter,
                               kernel=(k, embed), name="conv%d" % k)
        c = mx.sym.Activation(c, act_type="relu")
        c = mx.sym.Pooling(c, kernel=(seq_len - k + 1, 1),
                           pool_type="max")
        pooled.append(c)
    h = mx.sym.Flatten(mx.sym.Concat(*pooled, dim=1))
    if dropout:
        h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def synth(n, vocab, seq_len, rs):
    data = rs.randint(5, vocab, (n, seq_len)).astype("float32")
    y = rs.randint(0, 2, n).astype("float32")
    pat = [3, 1, 4]
    for i in range(n):
        if y[i] == 1:
            p = rs.randint(0, seq_len - len(pat))
            data[i, p:p + len(pat)] = pat
    return data, y


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    data, y = synth(args.num_examples, args.vocab, args.seq_len, rs)
    it = mx.io.NDArrayIter(data, y, batch_size=args.batch_size)
    mod = mx.mod.Module(get_symbol(args.vocab, args.seq_len),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy())
    score = dict(mod.score(it, mx.metric.Accuracy()))
    print("train accuracy %.4f" % score["accuracy"])
    return score["accuracy"]


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=50)
    p.add_argument("--seq-len", type=int, default=24)
    p.add_argument("--num-examples", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=8)
    main(p.parse_args())
