// Full-ABI client: drives the CachedOp, Autograd, DataIter and KVStore
// C-API groups end-to-end from one C++ binary — CSV data loaded through
// MXDataIter*, gradients computed through MXAutograd* over an
// MXInvokeCachedOp forward, parameters updated through MXKVStore* with
// a registered C updater.  No Python in this file.
//
// Reference analogue: the same training loop a Scala/C++ frontend runs
// against include/mxnet/c_api.h groups :680-760 (autograd), :1400-1500
// (data iter), :1513-1770 (kvstore), c_api_ndarray.cc:611 (CachedOp).
// Build: see README.md next to this file (same line as main.cc with
// full_abi.cc substituted).
#include <cmath>
#include <cstdio>
#include <fstream>
#include <map>
#include <string>
#include <vector>

#include "mxnet_tpu/cpp/mxnet_cpp.h"
#include "mxnet_tpu/cpp/op.h"

using mxnet_tpu::cpp::Check;
using mxnet_tpu::cpp::NDArray;
using mxnet_tpu::cpp::Symbol;

namespace {

constexpr mx_uint kBatch = 32, kDim = 8, kHidden = 16, kClasses = 3;
constexpr mx_uint kRows = 96;
constexpr float kLr = 0.5f;

// the C updater registered with MXKVStoreSetUpdater: local -= lr * recv
void SgdUpdater(int key, NDArrayHandle recv, NDArrayHandle local,
                void *handle) {
  (void)key;
  (void)handle;
  mx_uint nd = 0;
  const mx_uint *dims = nullptr;
  Check(MXNDArrayGetShape(local, &nd, &dims));
  size_t total = 1;
  for (mx_uint i = 0; i < nd; ++i) total *= dims[i];
  std::vector<float> w(total), g(total);
  Check(MXNDArraySyncCopyToCPU(local, w.data(), w.size()));
  Check(MXNDArraySyncCopyToCPU(recv, g.data(), g.size()));
  for (size_t i = 0; i < total; ++i) w[i] -= kLr * g[i];
  Check(MXNDArraySyncCopyFromCPU(local, w.data(), w.size()));
}

}  // namespace

int main() {
  // ---- synthetic separable task written as CSV ----
  unsigned seed = 4242;
  auto frand = [&seed]() {
    seed = seed * 1103515245u + 12345u;
    return static_cast<float>((seed >> 16) & 0x7fff) / 32768.0f - 0.5f;
  };
  std::vector<float> w_true(kDim * kClasses);
  for (auto &v : w_true) v = frand();
  {
    std::ofstream dcsv("full_abi_data.csv"), lcsv("full_abi_label.csv");
    for (mx_uint i = 0; i < kRows; ++i) {
      std::vector<float> x(kDim);
      float best = -1e30f;
      int cls = 0;
      for (mx_uint j = 0; j < kDim; ++j) x[j] = frand();
      for (mx_uint c = 0; c < kClasses; ++c) {
        float s = 0;
        for (mx_uint j = 0; j < kDim; ++j)
          s += x[j] * w_true[j * kClasses + c];
        if (s > best) { best = s; cls = static_cast<int>(c); }
      }
      for (mx_uint j = 0; j < kDim; ++j)
        dcsv << x[j] << (j + 1 == kDim ? '\n' : ',');
      lcsv << cls << '\n';
    }
  }

  // ---- MXDataIter*: find CSVIter in the creator registry ----
  mx_uint n_iters = 0;
  DataIterCreator *iters = nullptr;
  Check(MXListDataIters(&n_iters, &iters));
  DataIterCreator csv_creator = nullptr;
  for (mx_uint i = 0; i < n_iters; ++i) {
    const char *nm = nullptr;
    Check(MXDataIterGetIterInfo(iters[i], &nm, nullptr, nullptr, nullptr,
                                nullptr, nullptr));
    if (std::string(nm) == "CSVIter") csv_creator = iters[i];
  }
  if (!csv_creator) { std::printf("CSVIter not found\n"); return 1; }
  const char *ikeys[] = {"data_csv", "data_shape", "label_csv",
                         "batch_size"};
  const char *ivals[] = {"full_abi_data.csv", "(8,)",
                         "full_abi_label.csv", "32"};
  DataIterHandle it = nullptr;
  Check(MXDataIterCreateIter(csv_creator, 4, ikeys, ivals, &it));

  // ---- symbol + CachedOp ----
  Symbol data = Symbol::Variable("data");
  Symbol fc1 = mxnet_tpu::cpp::op::FullyConnected(
      "fc1", {data}, {{"num_hidden", std::to_string(kHidden)}});
  Symbol act = mxnet_tpu::cpp::op::Activation(
      "act", {fc1}, {{"act_type", "relu"}});
  Symbol fc2 = mxnet_tpu::cpp::op::FullyConnected(
      "fc2", {act}, {{"num_hidden", std::to_string(kClasses)}});
  Symbol net = mxnet_tpu::cpp::op::SoftmaxOutput(
      "softmax", {fc2}, {{"normalization", "batch"}});
  CachedOpHandle cop = nullptr;
  Check(MXCreateCachedOp(net.get(), &cop));

  auto args = net.ListArguments();   // data, fc1_w, fc1_b, fc2_w, fc2_b,
                                     // softmax_label
  auto shapes = net.InferArgShapes(
      {{"data", {kBatch, kDim}}, {"softmax_label", {kBatch}}});

  // ---- parameters + grads; init through MXKVStore* ----
  KVStoreHandle kv = nullptr;
  Check(MXKVStoreCreate("local", &kv));
  const char *kv_type = nullptr;
  Check(MXKVStoreGetType(kv, &kv_type));
  int rank = -1, size = 0, is_worker = 0;
  Check(MXKVStoreGetRank(kv, &rank));
  Check(MXKVStoreGetGroupSize(kv, &size));
  Check(MXKVStoreIsWorkerNode(&is_worker));
  std::printf("kvstore type=%s rank=%d/%d worker=%d\n", kv_type, rank,
              size, is_worker);
  Check(MXKVStoreSetUpdater(kv, SgdUpdater, nullptr));

  std::map<std::string, NDArray> params, grads;
  std::vector<std::string> pnames;
  for (size_t i = 0; i < args.size(); ++i) {
    if (args[i] == "data" || args[i] == "softmax_label") continue;
    NDArray arr(shapes[i]);
    size_t total = 1;
    for (mx_uint d : shapes[i]) total *= d;
    std::vector<float> init(total);
    float scale = std::sqrt(2.0f / static_cast<float>(
        shapes[i].size() > 1 ? shapes[i][1] : shapes[i][0]));
    for (auto &v : init) v = frand() * 2.0f * scale;
    arr.SyncCopyFromCPU(init);
    params.emplace(args[i], arr);
    grads.emplace(args[i], NDArray(shapes[i]));
    pnames.push_back(args[i]);
    int key = static_cast<int>(pnames.size()) - 1;
    NDArrayHandle vh = arr.get();
    Check(MXKVStoreInit(kv, 1, &key, &vh));
  }

  // ---- mark parameters for autograd (req 1 = write) ----
  {
    std::vector<NDArrayHandle> vars, gbufs;
    std::vector<mx_uint> reqs;
    for (auto &n : pnames) {
      vars.push_back(params[n].get());
      gbufs.push_back(grads[n].get());
      reqs.push_back(1);
    }
    Check(MXAutogradMarkVariables(
        static_cast<mx_uint>(vars.size()), vars.data(), reqs.data(),
        gbufs.data()));
  }

  // ---- training epochs: DataIter -> CachedOp fwd (recorded) ->
  //      MXAutogradBackward -> kvstore push/pull ----
  for (int epoch = 0; epoch < 60; ++epoch) {
    Check(MXDataIterBeforeFirst(it));
    int has_next = 0;
    Check(MXDataIterNext(it, &has_next));
    while (has_next) {
      NDArrayHandle bdata = nullptr, blabel = nullptr;
      Check(MXDataIterGetData(it, &bdata));
      Check(MXDataIterGetLabel(it, &blabel));

      int prev_rec = 0, prev_train = 0;
      Check(MXAutogradSetIsRecording(1, &prev_rec));
      Check(MXAutogradSetIsTraining(1, &prev_train));
      std::vector<NDArrayHandle> cop_in = {
          bdata, params["fc1_weight"].get(), params["fc1_bias"].get(),
          params["fc2_weight"].get(), params["fc2_bias"].get(), blabel};
      int n_out = 0;
      NDArrayHandle *outs = nullptr;
      Check(MXInvokeCachedOp(cop, static_cast<int>(cop_in.size()),
                             cop_in.data(), &n_out, &outs));
      unsigned char recording = 0;
      Check(MXAutogradIsRecording(&recording));
      if (!recording) { std::printf("recording flag lost\n"); return 1; }
      Check(MXAutogradBackward(1, &outs[0], nullptr, 0));
      Check(MXAutogradSetIsRecording(0, &prev_rec));
      Check(MXAutogradSetIsTraining(0, &prev_train));
      for (int oi = 0; oi < n_out; ++oi) Check(MXNDArrayFree(outs[oi]));

      // push grads / pull updated params through the kvstore
      for (size_t i = 0; i < pnames.size(); ++i) {
        int key = static_cast<int>(i);
        NDArrayHandle gh = grads[pnames[i]].get();
        NDArrayHandle ph = params[pnames[i]].get();
        Check(MXKVStorePush(kv, 1, &key, &gh, 0));
        Check(MXKVStorePull(kv, 1, &key, &ph, 0));
      }
      Check(MXDataIterNext(it, &has_next));
    }
  }

  // ---- score: full pass, recording off ----
  Check(MXDataIterBeforeFirst(it));
  int has_next = 0, correct = 0, total_n = 0;
  Check(MXDataIterNext(it, &has_next));
  while (has_next) {
    NDArrayHandle bdata = nullptr, blabel = nullptr;
    Check(MXDataIterGetData(it, &bdata));
    Check(MXDataIterGetLabel(it, &blabel));
    std::vector<NDArrayHandle> cop_in = {
        bdata, params["fc1_weight"].get(), params["fc1_bias"].get(),
        params["fc2_weight"].get(), params["fc2_bias"].get(), blabel};
    int n_out = 0;
    NDArrayHandle *outs = nullptr;
    Check(MXInvokeCachedOp(cop, static_cast<int>(cop_in.size()),
                           cop_in.data(), &n_out, &outs));
    std::vector<float> probs(kBatch * kClasses), lab(kBatch);
    Check(MXNDArraySyncCopyToCPU(outs[0], probs.data(), probs.size()));
    Check(MXNDArraySyncCopyToCPU(blabel, lab.data(), lab.size()));
    int pad = 0;
    Check(MXDataIterGetPadNum(it, &pad));
    for (mx_uint i = 0; i < kBatch - static_cast<mx_uint>(pad); ++i) {
      int best = 0;
      for (mx_uint c = 1; c < kClasses; ++c)
        if (probs[i * kClasses + c] > probs[i * kClasses + best])
          best = static_cast<int>(c);
      correct += (best == static_cast<int>(lab[i]));
      ++total_n;
    }
    for (int oi = 0; oi < n_out; ++oi) Check(MXNDArrayFree(outs[oi]));
    Check(MXDataIterNext(it, &has_next));
  }
  float acc = static_cast<float>(correct) /
              static_cast<float>(total_n ? total_n : 1);
  std::printf("accuracy %.3f over %d rows\n", acc, total_n);

  Check(MXKVStoreBarrier(kv));
  Check(MXKVStoreFree(kv));
  Check(MXDataIterFree(it));
  Check(MXFreeCachedOp(cop));
  if (acc > 0.9f) {
    std::printf("FULL ABI CLIENT OK\n");
    return 0;
  }
  std::printf("accuracy too low\n");
  return 1;
}
