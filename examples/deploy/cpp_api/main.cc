// cpp-package-style client: build a symbol with the generated op
// frontend, bind an executor, TRAIN with backward + the fused sgd
// update invoked imperatively, then score — every step through the
// native C ABI (include/mxnet_tpu/c_api.h), no Python in this file.
//
// Reference analogue: cpp-package/example/mlp.cpp over
// include/mxnet-cpp/.  Build: see README.md next to this file.
#include <cmath>
#include <cstdio>
#include <map>
#include <string>
#include <vector>

#include "mxnet_tpu/cpp/mxnet_cpp.h"
#include "mxnet_tpu/cpp/op.h"

using mxnet_tpu::cpp::Check;
using mxnet_tpu::cpp::Executor;
using mxnet_tpu::cpp::NDArray;
using mxnet_tpu::cpp::Symbol;

int main() {
  const mx_uint kBatch = 64, kDim = 8, kHidden = 16, kClasses = 3;

  // ---- symbol: 2-layer MLP + softmax loss (generated op functions;
  // weight/bias variables auto-created at compose) ----
  Symbol data = Symbol::Variable("data");
  Symbol fc1 = mxnet_tpu::cpp::op::FullyConnected(
      "fc1", {data}, {{"num_hidden", std::to_string(kHidden)}});
  Symbol act = mxnet_tpu::cpp::op::Activation(
      "act", {fc1}, {{"act_type", "relu"}});
  Symbol fc2 = mxnet_tpu::cpp::op::FullyConnected(
      "fc2", {act}, {{"num_hidden", std::to_string(kClasses)}});
  Symbol net = mxnet_tpu::cpp::op::SoftmaxOutput(
      "softmax", {fc2}, {{"normalization", "batch"}});

  auto args = net.ListArguments();
  std::printf("arguments:");
  for (auto &a : args) std::printf(" %s", a.c_str());
  std::printf("\n");

  // ---- shape inference from the data/label shapes ----
  auto shapes = net.InferArgShapes(
      {{"data", {kBatch, kDim}}, {"softmax_label", {kBatch}}});

  // ---- synthetic separable task ----
  std::vector<float> X(kBatch * kDim), y(kBatch);
  unsigned seed = 12345;
  auto frand = [&seed]() {
    seed = seed * 1103515245u + 12345u;
    return static_cast<float>((seed >> 16) & 0x7fff) / 32768.0f - 0.5f;
  };
  std::vector<float> w_true(kDim * kClasses);
  for (auto &v : w_true) v = frand();
  for (mx_uint i = 0; i < kBatch; ++i) {
    float best = -1e30f;
    int cls = 0;
    for (mx_uint j = 0; j < kDim; ++j) X[i * kDim + j] = frand();
    for (mx_uint c = 0; c < kClasses; ++c) {
      float s = 0;
      for (mx_uint j = 0; j < kDim; ++j)
        s += X[i * kDim + j] * w_true[j * kClasses + c];
      if (s > best) { best = s; cls = static_cast<int>(c); }
    }
    y[i] = static_cast<float>(cls);
  }

  // ---- argument + gradient arrays ----
  std::map<std::string, NDArray> arg_arrays, grad_arrays;
  std::map<std::string, mx_uint> grad_reqs;
  for (size_t i = 0; i < args.size(); ++i) {
    NDArray arr(shapes[i]);
    if (args[i] == "data") {
      arr.SyncCopyFromCPU(X);
      grad_reqs[args[i]] = 0;
    } else if (args[i] == "softmax_label") {
      arr.SyncCopyFromCPU(y);
      grad_reqs[args[i]] = 0;
    } else {
      // xavier-ish init
      size_t total = 1;
      for (mx_uint d : shapes[i]) total *= d;
      std::vector<float> init(total);
      float scale = std::sqrt(2.0f / static_cast<float>(
          shapes[i].size() > 1 ? shapes[i][1] : shapes[i][0]));
      for (auto &v : init) v = frand() * 2.0f * scale;
      arr.SyncCopyFromCPU(init);
      grad_arrays.emplace(args[i], NDArray(shapes[i]));
      grad_reqs[args[i]] = 1;  // write
    }
    arg_arrays.emplace(args[i], arr);
  }

  Executor exec(net, arg_arrays, grad_arrays, grad_reqs);

  // ---- the fused sgd update op, invoked imperatively per param ----
  mx_uint n_ops = 0;
  AtomicSymbolCreator *creators = nullptr;
  Check(MXSymbolListAtomicSymbolCreators(&n_ops, &creators));
  AtomicSymbolCreator sgd = nullptr;
  for (mx_uint i = 0; i < n_ops; ++i) {
    const char *nm = nullptr;
    Check(MXSymbolGetAtomicSymbolName(creators[i], &nm));
    if (std::string(nm) == "sgd_update") sgd = creators[i];
  }
  if (!sgd) { std::printf("sgd_update op not found\n"); return 1; }

  for (int epoch = 0; epoch < 200; ++epoch) {
    exec.Forward(true);
    exec.Backward();
    for (auto &kv : grad_arrays) {
      NDArrayHandle io[2] = {arg_arrays[kv.first].get(),
                             kv.second.get()};
      int n_out = 0;
      NDArrayHandle *outs = nullptr;
      const char *keys[] = {"lr"};
      const char *vals[] = {"0.5"};
      Check(MXImperativeInvoke(sgd, 2, io, &n_out, &outs, 1, keys,
                               vals));
      // write the updated weight back (functional update semantics)
      mx_uint nd;
      const mx_uint *dims;
      Check(MXNDArrayGetShape(outs[0], &nd, &dims));
      size_t total = 1;
      for (mx_uint d = 0; d < nd; ++d) total *= dims[d];
      std::vector<float> host(total);
      Check(MXNDArraySyncCopyToCPU(outs[0], host.data(), host.size()));
      arg_arrays[kv.first].SyncCopyFromCPU(host);
      for (int oi = 0; oi < n_out; ++oi) Check(MXNDArrayFree(outs[oi]));
    }
  }

  // ---- score ----
  exec.Forward(false);
  auto outs = exec.Outputs();
  auto probs = outs[0].SyncCopyToCPU();
  int correct = 0;
  for (mx_uint i = 0; i < kBatch; ++i) {
    int argmax = 0;
    for (mx_uint c = 1; c < kClasses; ++c)
      if (probs[i * kClasses + c] > probs[i * kClasses + argmax])
        argmax = static_cast<int>(c);
    if (argmax == static_cast<int>(y[i])) ++correct;
  }
  float acc = static_cast<float>(correct) / kBatch;
  std::printf("train accuracy: %.3f\n", acc);

  // round-trip the graph through JSON (checkpoint format parity)
  Symbol loaded = Symbol::FromJSON(net.ToJSON());
  std::printf("json round-trip outputs: %s\n",
              loaded.ListOutputs()[0].c_str());
  if (acc < 0.9f) { std::printf("FAILED: accuracy too low\n"); return 1; }
  std::printf("CPP API CLIENT OK\n");
  return 0;
}
