#!/usr/bin/env python
"""Deployment walkthrough (reference ``amalgamation/`` +
``c_predict_api``): train → checkpoint → AOT bundle → serve four ways.

    python examples/deploy/export_and_serve.py

1. ``Predictor`` — forward-only serving from checkpoint files.
2. ``Predictor.export`` → one ``.mxtpu`` artifact (serialized
   multi-platform StableHLO + params); ``ExportedPredictor`` serves it
   with only ``jax.export`` + numpy.
3. The C ABI (``include/mxnet_tpu/c_predict_api.h``) — see
   ``tests/test_deploy_tools.py::test_c_predict_api`` for a full C
   client; this script prints the compile line.
4. The continuous-batching generation queue — an LM checkpoint restored
   into ``serve.InferenceSession`` (bucketed AOT prefill + paged-KV
   decode) and driven by ``serve.Scheduler`` over an arrival trace.
   See ``docs/serving.md`` and ``bench_serve.py``.
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def main():
    rs = np.random.RandomState(0)
    X = rs.rand(256, 16).astype("float32")
    W = rs.rand(16, 4).astype("float32")
    y = (X @ W).argmax(1).astype("float32")

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(mx.sym.FullyConnected(
                mx.sym.Variable("data"), num_hidden=32, name="fc1"),
                act_type="relu"),
            num_hidden=4, name="fc2"), name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(net, context=mx.tpu())
    mod.fit(it, num_epoch=30, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5})

    workdir = tempfile.mkdtemp(prefix="mxtpu_deploy_")
    prefix = os.path.join(workdir, "model")
    mod.save_checkpoint(prefix, 30)

    # 1. serve from checkpoint files
    pred = mx.Predictor.load(prefix, 30, {"data": (8, 16)})
    pred.set_input("data", X[:8])
    ref = pred.forward()[0].asnumpy()
    print("predictor output", ref.shape, "acc on sample:",
          (ref.argmax(1) == y[:8]).mean())

    # 2. one-file AOT bundle
    bundle = prefix + ".mxtpu"
    pred.export(bundle)
    print("bundle:", bundle, os.path.getsize(bundle), "bytes")
    served = mx.Predictor.load_exported(bundle)
    out = served.forward(data=X[:8])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    print("ExportedPredictor matches:", out.shape)

    # 3. the C ABI build line (full client in tests/test_deploy_tools.py)
    print("\nC serving: build the ABI once with\n"
          "  python -c \"from mxnet_tpu import _native; "
          "_native._load('c_predict_api')\"\n"
          "then link clients against mxnet_tpu/_build/c_predict_api.so "
          "with -I include/ and run with MXNET_TPU_HOME set.")

    # 4. continuous-batching generation queue over a paged KV cache
    from mxnet_tpu import checkpoint as ckpt
    from mxnet_tpu import serve

    lm_cfg = serve.ModelConfig(vocab_size=96, num_layers=2, d_model=32,
                               num_heads=2, max_len=64)
    lm_params = serve.init_params(lm_cfg, seed=0)  # stands in for a run
    ckpt.CheckpointManager(workdir, prefix="lm",
                           save_optimizer_states=False).save(
        epoch=1, arg_params=lm_params)

    # every executable (one prefill per bucket + one decode step) is
    # AOT-compiled here; steady-state serving never traces
    sess = serve.InferenceSession.from_checkpoint(
        workdir, prefix="lm", num_heads=lm_cfg.num_heads,
        config=serve.ServeConfig(slots=4, page_size=8, buckets=(8, 16),
                                 max_new=12))
    rs = np.random.RandomState(1)
    requests = [
        serve.Request(rid=i,
                      prompt=rs.randint(1, 95, size=plen).tolist(),
                      max_new=12, arrival_s=0.004 * i)
        for i, plen in enumerate((5, 9, 13, 6, 11, 7))]
    done, makespan = serve.Scheduler(sess, policy="continuous") \
        .run(requests)
    stats = serve.summarize(done, makespan)
    print("\ncontinuous batching: %d requests, %d tokens, "
          "%.0f tok/s, ttft p99 %.1f ms"
          % (stats["completed"], stats["total_tokens"],
             stats["tokens_per_sec"], stats["ttft_p99_s"] * 1e3))
    print("executables:", sorted(sess.executables),
          "fallbacks:", sess.fallback_count())


if __name__ == "__main__":
    main()
