#!/usr/bin/env python
"""FCN-xs semantic segmentation (reference ``example/fcn-xs/``:
``symbol_fcnxs.py`` — conv encoder, 1x1 score heads, Deconvolution
upsampling with a skip fusion, per-pixel ``SoftmaxOutput``
``multi_output=True``).

The capability this proves: Deconvolution at segmentation scale — the
transposed-conv upsampling path and the fcn-16s-style skip sum — plus
the multi-output per-pixel softmax, trained end-to-end through
``Module.fit``.

Synthetic task: images containing a bright disk on textured background;
the label map marks disk pixels.  Pixel accuracy must exceed 0.9.

    python examples/fcn-xs/fcn_xs.py --num-epochs 6
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(num_classes=2):
    """Encoder /4, score head, 2x deconv + skip (fcn-16s pattern,
    ``symbol_fcnxs.py:60-100``), then a final 2x deconv to full res."""
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                            pad=(1, 1), name="conv1")
    c1 = mx.sym.Activation(mx.sym.BatchNorm(c1, name="bn1"),
                           act_type="relu")
    p1 = mx.sym.Pooling(c1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")                      # /2
    c2 = mx.sym.Convolution(p1, num_filter=32, kernel=(3, 3),
                            pad=(1, 1), name="conv2")
    c2 = mx.sym.Activation(mx.sym.BatchNorm(c2, name="bn2"),
                           act_type="relu")
    p2 = mx.sym.Pooling(c2, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")                      # /4
    c3 = mx.sym.Convolution(p2, num_filter=32, kernel=(3, 3),
                            pad=(1, 1), name="conv3")
    c3 = mx.sym.Activation(mx.sym.BatchNorm(c3, name="bn3"),
                           act_type="relu")

    # score heads (1x1 convs) at /4 and /2, fused fcn-16s style
    score4 = mx.sym.Convolution(c3, num_filter=num_classes,
                                kernel=(1, 1), name="score4")
    up2 = mx.sym.Deconvolution(score4, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=num_classes,
                               no_bias=True, name="up2")      # /2
    score2 = mx.sym.Convolution(p1, num_filter=num_classes,
                                kernel=(1, 1), name="score2")
    fused = up2 + score2
    up1 = mx.sym.Deconvolution(fused, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=num_classes,
                               no_bias=True, name="up1")      # /1
    return mx.sym.SoftmaxOutput(up1, multi_output=True,
                                normalization="batch",
                                name="softmax")


def synth_batch(n, size, rs):
    """Disk of random center/radius on a textured background."""
    imgs = 0.3 * rs.randn(n, 3, size, size).astype("float32")
    labels = np.zeros((n, size, size), "float32")
    yy, xx = np.mgrid[0:size, 0:size]
    for i in range(n):
        cy, cx = rs.randint(size // 4, 3 * size // 4, 2)
        r2 = rs.randint(2, size // 3) ** 2
        mask = ((yy - cy) ** 2 + (xx - cx) ** 2) < r2
        labels[i][mask] = 1.0
        imgs[i, :, mask] += 1.5
    return imgs, labels


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    imgs, labels = synth_batch(args.num_examples, args.size, rs)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=args.batch_size)
    net = get_symbol()
    mod = mx.mod.Module(net, context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss())

    # per-pixel accuracy on a fresh batch
    test_imgs, test_labels = synth_batch(args.batch_size, args.size, rs)
    mod.forward(mx.io.DataBatch([mx.nd.array(test_imgs)], []),
                is_train=False)
    pred = mod.get_outputs()[0].asnumpy()       # (N, C, H, W)
    pix_acc = float((pred.argmax(1) == test_labels).mean())
    print("pixel accuracy %.4f" % pix_acc)
    return pix_acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=6)
    main(p.parse_args())
