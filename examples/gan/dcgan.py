#!/usr/bin/env python
"""DCGAN — adversarial training with TWO alternating Modules
(reference ``example/gan/dcgan.py``): a generator Module (Deconvolution
stack) and a discriminator Module bound with ``inputs_need_grad=True``;
the generator trains on the gradient the discriminator produces w.r.t.
its INPUT, handed across modules via ``modG.backward(diffD)`` — the
training pattern nothing in single-Module ``fit`` exercises:

* D steps on fake + real with manual gradient accumulation across the
  two passes (saved ``grad_dict`` arrays added before ``update()``),
* G steps through ``modD.get_input_grads()``.

Data: synthetic 'disk' images (bright center disk, dark rim).  Learning
is asserted the GAN way: the generator's samples move from noise toward
the real statistics, and fool rate rises off the floor.

    python examples/gan/dcgan.py --num-epochs 10
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_generator(ngf, z_dim):
    """z (N, Z, 1, 1) -> image (N, 1, 8, 8) via Deconvolution stack."""
    rand = mx.sym.Variable("rand")
    g = mx.sym.Deconvolution(rand, kernel=(4, 4), num_filter=ngf * 2,
                             no_bias=True, name="g1")          # 4x4
    g = mx.sym.BatchNorm(g, fix_gamma=True, eps=1e-5, name="gbn1")
    g = mx.sym.Activation(g, act_type="relu", name="gact1")
    g = mx.sym.Deconvolution(g, kernel=(4, 4), stride=(2, 2),
                             pad=(1, 1), num_filter=ngf,
                             no_bias=True, name="g2")          # 8x8
    g = mx.sym.BatchNorm(g, fix_gamma=True, eps=1e-5, name="gbn2")
    g = mx.sym.Activation(g, act_type="relu", name="gact2")
    g = mx.sym.Deconvolution(g, kernel=(3, 3), pad=(1, 1), num_filter=1,
                             no_bias=True, name="g3")          # 8x8
    return mx.sym.Activation(g, act_type="tanh", name="gout")


def make_discriminator(ndf):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    d = mx.sym.Convolution(data, kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ndf, no_bias=True,
                           name="d1")                          # 4x4
    d = mx.sym.LeakyReLU(d, act_type="leaky", slope=0.2, name="dact1")
    d = mx.sym.Convolution(d, kernel=(4, 4), num_filter=1,
                           no_bias=True, name="d2")            # 1x1
    d = mx.sym.Flatten(d)
    return mx.sym.LogisticRegressionOutput(d, label, name="dloss")


def real_batch(n, rs):
    """Bright center disk on a dark field, in [-1, 1]."""
    yy, xx = np.mgrid[0:8, 0:8]
    disk = (((yy - 3.5) ** 2 + (xx - 3.5) ** 2) < 6).astype("float32")
    imgs = np.tile(disk, (n, 1, 1, 1)) * 1.6 - 0.8
    imgs += 0.1 * rs.randn(n, 1, 8, 8).astype("float32")
    return np.clip(imgs, -1, 1).astype("float32")


def main(args):
    rs = np.random.RandomState(0)
    # parameter initializers are pure functions of (mx.random seed,
    # parameter name); pin the seed so the adversarial dynamics
    # (seed-sensitive by nature) reproduce
    mx.random.seed(3)
    batch, z_dim = args.batch_size, 16
    ctx = mx.tpu(0)

    symG = make_generator(ngf=16, z_dim=z_dim)
    symD = make_discriminator(ndf=16)

    modG = mx.mod.Module(symG, data_names=("rand",), label_names=(),
                         context=ctx)
    modG.bind(data_shapes=[("rand", (batch, z_dim, 1, 1))])
    modG.init_params(mx.init.Normal(0.05))
    modG.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    modD = mx.mod.Module(symD, data_names=("data",),
                         label_names=("label",), context=ctx)
    modD.bind(data_shapes=[("data", (batch, 1, 8, 8))],
              label_shapes=[("label", (batch,))],
              inputs_need_grad=True)
    modD.init_params(mx.init.Normal(0.05))
    modD.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    ones = mx.nd.ones((batch,))
    zeros = mx.nd.zeros((batch,))
    real_mean = float(real_batch(256, rs).mean())
    fool_rate = 0.0
    first_gap = None

    for epoch in range(args.num_epochs):
        d_correct, d_total, fooled = 0, 0, 0
        for _ in range(args.batches_per_epoch):
            z = mx.nd.array(rs.randn(batch, z_dim, 1, 1)
                            .astype("float32"))
            modG.forward(mx.io.DataBatch([z], []), is_train=True)
            fake = modG.get_outputs()[0]

            # --- D on fake (label 0): save grads, defer update -------
            modD.forward(mx.io.DataBatch([fake], [zeros]),
                         is_train=True)
            modD.backward()
            saved = {n: g.copy()
                     for n, g in modD._exec.grad_dict.items()
                     if g is not None and n not in ("data", "label")}
            p = modD.get_outputs()[0].asnumpy().ravel()
            d_correct += int((p < 0.5).sum())
            d_total += batch

            # --- D on real (label 1): accumulate saved fake grads ----
            xb = mx.nd.array(real_batch(batch, rs))
            modD.forward(mx.io.DataBatch([xb], [ones]), is_train=True)
            modD.backward()
            for n, g in saved.items():
                modD._exec.grad_dict[n].__iadd__(g)
            modD.update()
            p = modD.get_outputs()[0].asnumpy().ravel()
            d_correct += int((p > 0.5).sum())
            d_total += batch

            # --- G step: label fake as real, push D's input gradient
            #     back through G ------------------------------------
            modD.forward(mx.io.DataBatch([fake], [ones]), is_train=True)
            modD.backward()
            diffD = modD.get_input_grads()
            modG.backward([diffD[0]])
            modG.update()
            p = modD.get_outputs()[0].asnumpy().ravel()
            fooled += int((p > 0.5).sum())

        fake_np = fake.asnumpy()
        gap = abs(float(fake_np.mean()) - real_mean)
        if first_gap is None:
            first_gap = gap
        fool_rate = fooled / d_total * 2
        print("epoch %d D-acc %.3f fool-rate %.3f fake-mean-gap %.3f"
              % (epoch, d_correct / d_total, fool_rate, gap))

    print("final fake-mean-gap %.3f (start %.3f) fool-rate %.3f"
          % (gap, first_gap, fool_rate))
    return gap, fool_rate


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--batches-per-epoch", type=int, default=20)
    p.add_argument("--lr", type=float, default=2e-4)
    main(p.parse_args())
