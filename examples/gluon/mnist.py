#!/usr/bin/env python
"""Gluon imperative MNIST training (reference ``example/gluon/mnist.py``):
``nn.Sequential`` + ``autograd.record`` + ``Trainer.step``.

    python examples/gluon/mnist.py --epochs 5
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def make_net(hybridize):
    net = nn.Sequential()
    net.add(nn.Dense(128, activation="relu"))
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(10))
    if hybridize:
        net.hybridize()
    return net


def synthetic_mnist(n, rs):
    x = rs.rand(n, 784).astype("float32") * 0.1
    y = rs.randint(0, 10, n).astype("float32")
    for i in range(n):
        k = int(y[i])
        x[i, 28 * k: 28 * k + 56] += 0.9
    return x, y


def evaluate(net, loader):
    metric = mx.metric.Accuracy()
    for data, label in loader:
        metric.update([label], [net(data)])
    return metric.get()[1]


def main(args):
    rs = np.random.RandomState(0)
    xtr, ytr = synthetic_mnist(args.num_examples, rs)
    xva, yva = synthetic_mnist(1024, rs)
    train_data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(xtr, ytr), batch_size=args.batch_size,
        shuffle=True)
    val_data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(xva, yva), batch_size=args.batch_size)

    net = make_net(args.hybridize)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total = 0.0
        for data, label in train_data:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            total += float(loss.asnumpy().mean())
        acc = evaluate(net, val_data)
        print("epoch %d loss %.4f val-acc %.4f" % (epoch, total, acc))
    return evaluate(net, val_data)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--num-examples", type=int, default=8192)
    p.add_argument("--hybridize", action=argparse.BooleanOptionalAction,
                   default=True)
    main(p.parse_args())
