#!/usr/bin/env python
"""Gluon-native mixture-of-experts training: ``gluon.nn.MoE`` (routed
top-k dispatch, ``parallel/expert.py``) inside a HybridBlock classifier,
trained with ``autograd.record`` + ``Trainer.step`` and the Switch-style
load-balancing aux loss added to the objective — the imperative face of
the same routed MoE the symbolic ``MoE`` op / ``models.transformer``
expose.

    python examples/gluon/moe_classifier.py --num-epochs 30
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class MoEClassifier(gluon.HybridBlock):
    """Dense stem -> routed-MoE feed-forward -> linear head."""

    def __init__(self, num_classes, num_experts, hidden, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.stem = gluon.nn.Dense(32, activation="relu")
            self.moe = gluon.nn.MoE(num_experts=num_experts,
                                    hidden_size=hidden, top_k=2)
            self.head = gluon.nn.Dense(num_classes)

    def forward(self, x):
        h = self.stem(x)
        moe_out, aux = self.moe(h)
        return self.head(h + moe_out), aux


def main(args):
    rs = np.random.RandomState(0)
    x = rs.randn(args.num_examples, 16).astype("float32")
    w_true = rs.randn(16, args.num_classes).astype("float32")
    y = (x @ w_true).argmax(axis=1).astype("float32")

    dataset = gluon.data.ArrayDataset(x, y)
    loader = gluon.data.DataLoader(dataset, batch_size=args.batch_size,
                                   shuffle=True)

    net = MoEClassifier(args.num_classes, args.num_experts, 32)
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    acc = 0.0
    aux_total, nb = 0.0, 1
    for epoch in range(args.num_epochs):
        total = aux_total = 0.0
        nb = 0
        for data, label in loader:
            with autograd.record():
                out, aux = net(data)
                loss = loss_fn(out, label) + args.aux_coef * aux
            loss.backward()
            trainer.step(data.shape[0])
            total += float(loss.asnumpy().mean())
            aux_total += float(aux.asnumpy())
            nb += 1
        correct = n = 0
        for data, label in loader:
            out, _ = net(data)
            correct += int((out.asnumpy().argmax(axis=1) ==
                            label.asnumpy()).sum())
            n += data.shape[0]
        acc = correct / n
        logging.info("epoch %d loss %.4f balance %.3f acc %.4f",
                     epoch, total / nb, aux_total / nb, acc)
    print("final accuracy: %.4f (balance loss %.3f; 1.0 = perfectly "
          "balanced experts)" % (acc, aux_total / nb))
    if acc > 0.9:
        print("GLUON MOE TRAINS OK")
        return 0
    print("GLUON MOE DID NOT LEARN")
    return 1


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="gluon MoE classifier")
    p.add_argument("--num-epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=4)
    p.add_argument("--num-experts", type=int, default=4)
    p.add_argument("--num-examples", type=int, default=256)
    p.add_argument("--aux-coef", type=float, default=0.01)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--hybridize", action=argparse.BooleanOptionalAction,
                   default=True)
    sys.exit(main(p.parse_args()))
