"""Shared training harness for the image-classification examples
(reference ``example/image-classification/common/fit.py:108-205``): one
``fit(args, network, data_loader)`` that wires kvstore, optimizer,
LR schedule, checkpointing, and monitoring around ``Module.fit``.
"""
from __future__ import annotations

import argparse
import logging
import os

import mxnet_tpu as mx


def add_fit_args(parser):
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="mlp")
    train.add_argument("--num-layers", type=int, default=None)
    train.add_argument("--gpus", type=str, default=None,
                       help="ignored on TPU; kept for script parity")
    train.add_argument("--kv-store", type=str, default="local")
    train.add_argument("--num-epochs", type=int, default=10)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default=None)
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--monitor", type=int, default=0)
    train.add_argument("--param-sharding", type=str, default=None,
                       choices=(None, "fsdp", "tp"),
                       help="TPU-native: shard parameters over the mesh")
    return train


def _lr_scheduler(args, epoch_size):
    if not args.lr_step_epochs:
        return args.lr, None
    begin = args.load_epoch or 0
    steps = [int(e) for e in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in steps:
        if begin >= s:
            lr *= args.lr_factor
    remaining = [epoch_size * (s - begin) for s in steps if s > begin]
    if not remaining:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(
        step=remaining, factor=args.lr_factor)


def fit(args, network, data_loader):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    kv = mx.kv.create(args.kv_store)
    train, val = data_loader(args, kv)

    epoch_size = getattr(args, "num_examples", 50000) // args.batch_size
    lr, sched = _lr_scheduler(args, epoch_size)

    checkpoint = None
    arg_params = aux_params = None
    if args.model_prefix:
        checkpoint = mx.callback.do_checkpoint(args.model_prefix)
        if args.load_epoch is not None:
            network, arg_params, aux_params = mx.model.load_checkpoint(
                args.model_prefix, args.load_epoch)

    mod = mx.mod.Module(network, context=mx.tpu())
    optimizer_params = {"learning_rate": lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom
    if sched is not None:
        optimizer_params["lr_scheduler"] = sched

    monitor = mx.Monitor(args.disp_batches, pattern=".*") \
        if args.monitor > 0 else None

    mod.fit(train,
            param_sharding=args.param_sharding,
            compute_dtype=getattr(args, "compute_dtype", None),
            eval_data=val,
            eval_metric=["accuracy"],
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params,
            aux_params=aux_params,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint,
            monitor=monitor)
    return mod
