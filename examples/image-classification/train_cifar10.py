#!/usr/bin/env python
"""Train ResNet on CIFAR-10 through the RecordIO pipeline (reference
``example/image-classification/train_cifar10.py``).

If ``--data-dir`` has no ``cifar10_train.rec``, a synthetic class-colored
dataset is packed into RecordIO first (via ``mxnet_tpu.recordio`` +
``tools/im2rec.py`` conventions), so the full pipeline — .rec file →
``ImageRecordIter`` (threaded decode + augmenters + prefetch) →
``Module.fit`` — runs hermetically.

    python examples/image-classification/train_cifar10.py --num-layers 20
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

import mxnet_tpu as mx
from common import fit


def _pack_synthetic(rec_path, n, num_classes, rs):
    """Pack class-colored 32x32 PNGs into a .rec (im2rec format)."""
    from PIL import Image
    import io as pyio

    from mxnet_tpu import recordio

    writer = recordio.MXRecordIO(rec_path, "w")
    for i in range(n):
        cls = int(rs.randint(num_classes))
        img = (rs.rand(32, 32, 3) * 60).astype("uint8")
        img[..., cls % 3] += np.uint8(120 + 10 * (cls // 3))
        bio = pyio.BytesIO()
        Image.fromarray(img).save(bio, format="PNG")
        header = recordio.IRHeader(0, float(cls), i, 0)
        writer.write(recordio.pack(header, bio.getvalue()))
    writer.close()


def get_cifar_iter(args, kv):
    data_dir = args.data_dir or "/tmp/cifar10_synth"
    os.makedirs(data_dir, exist_ok=True)
    train_rec = os.path.join(data_dir, "cifar10_train.rec")
    val_rec = os.path.join(data_dir, "cifar10_val.rec")
    if not os.path.exists(train_rec):
        rs = np.random.RandomState(0)
        _pack_synthetic(train_rec, args.num_examples, args.num_classes, rs)
        _pack_synthetic(val_rec, 512, args.num_classes, rs)
    train = mx.io.ImageRecordIter(
        path_imgrec=train_rec,
        data_shape=(3, 28, 28),
        batch_size=args.batch_size,
        rand_crop=True, rand_mirror=True, shuffle=True,
        part_index=kv.rank, num_parts=kv.num_workers)
    val = mx.io.ImageRecordIter(
        path_imgrec=val_rec,
        data_shape=(3, 28, 28),
        batch_size=args.batch_size)
    return train, val


def get_symbol(args):
    from mxnet_tpu.models import resnet

    return resnet.get_symbol(num_classes=args.num_classes,
                             num_layers=args.num_layers or 20,
                             image_shape=(3, 28, 28))


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=4096)
    parser.add_argument("--data-dir", type=str, default=None)
    fit.add_fit_args(parser)
    parser.set_defaults(network="resnet", num_layers=20, num_epochs=10,
                        batch_size=128, lr=0.05)
    args = parser.parse_args()
    fit.fit(args, get_symbol(args), get_cifar_iter)
