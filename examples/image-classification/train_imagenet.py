#!/usr/bin/env python
"""ImageNet training (reference
``example/image-classification/train_imagenet.py`` — the BASELINE.json
flagship configs: resnet-50 / inception-v3 over ``ImageRecordIter``).

Point ``--data-train``/``--data-val`` at ImageNet ``.rec`` files packed
with ``tools/im2rec.py``.  Without them, a synthetic class-colored .rec
set is packed at a reduced resolution so the full pipeline — sharded
RecordIO read, threaded decode + augmenters, background prefetch,
fused bf16 train step — runs hermetically.

    python examples/image-classification/train_imagenet.py \
        --network resnet --num-layers 50 --batch-size 256 \
        --compute-dtype bfloat16
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

import mxnet_tpu as mx
from common import fit


def get_symbol(args):
    from mxnet_tpu import models

    shape = tuple(int(x) for x in args.image_shape.split(","))
    kwargs = {"num_classes": args.num_classes}
    if args.network == "resnet":
        kwargs.update(num_layers=args.num_layers or 50,
                      image_shape=shape)
    return models.get_model(args.network, **kwargs)


def _pack_synthetic(rec_path, n, num_classes, size, rs):
    from PIL import Image
    import io as pyio

    from mxnet_tpu import recordio

    w = recordio.MXRecordIO(rec_path, "w")
    for i in range(n):
        cls = int(rs.randint(num_classes))
        img = (rs.rand(size, size, 3) * 50).astype("uint8")
        img[..., cls % 3] += np.uint8(100 + 8 * (cls // 3))
        bio = pyio.BytesIO()
        Image.fromarray(img).save(bio, format="JPEG", quality=90)
        w.write(recordio.pack(recordio.IRHeader(0, float(cls), i, 0),
                              bio.getvalue()))
    w.close()


def get_imagenet_iter(args, kv):
    shape = tuple(int(x) for x in args.image_shape.split(","))
    train_rec, val_rec = args.data_train, args.data_val
    if not (train_rec and os.path.exists(train_rec)):
        data_dir = "/tmp/imagenet_synth_%dpx" % shape[-1]
        os.makedirs(data_dir, exist_ok=True)
        train_rec = os.path.join(data_dir, "train.rec")
        val_rec = os.path.join(data_dir, "val.rec")
        if not os.path.exists(train_rec):
            rs = np.random.RandomState(0)
            side = shape[-1] + shape[-1] // 8
            _pack_synthetic(train_rec, args.num_examples,
                            args.num_classes, side, rs)
            _pack_synthetic(val_rec, max(256, args.batch_size),
                            args.num_classes, side, rs)
    train = mx.io.ImageRecordIter(
        path_imgrec=train_rec, data_shape=shape,
        batch_size=args.batch_size,
        rand_crop=True, rand_mirror=True, shuffle=True,
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        preprocess_threads=args.data_nthreads,
        part_index=kv.rank, num_parts=kv.num_workers)
    val = mx.io.ImageRecordIter(
        path_imgrec=val_rec, data_shape=shape,
        batch_size=args.batch_size,
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        preprocess_threads=args.data_nthreads)
    return train, val


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train imagenet",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=4096,
                        help="synthetic-set size when no --data-train")
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--data-nthreads", type=int, default=8)
    parser.add_argument("--compute-dtype", type=str, default=None)
    fit.add_fit_args(parser)
    parser.set_defaults(network="resnet", num_layers=50, num_epochs=2,
                        batch_size=128, lr=0.1,
                        lr_step_epochs="30,60", num_examples=4096)
    args = parser.parse_args()
    args.num_examples = args.num_examples  # used by fit's epoch_size
    fit.fit(args, get_symbol(args), get_imagenet_iter)
