#!/usr/bin/env python
"""Train MLP/LeNet on MNIST (reference
``example/image-classification/train_mnist.py``).

Uses the real MNIST files when ``--data-dir`` points at the idx-format
gz/ubyte files; otherwise falls back to a synthetic MNIST-shaped dataset
so the script runs hermetically (this image has no network egress).

    python examples/image-classification/train_mnist.py --network lenet \
        --num-epochs 5
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

import mxnet_tpu as mx
from common import fit


def get_symbol(network, num_classes=10):
    from mxnet_tpu.models import lenet, mlp

    if network == "mlp":
        return mlp.get_symbol(num_classes=num_classes)
    if network == "lenet":
        return lenet.get_symbol(num_classes=num_classes)
    raise ValueError("unknown network %r" % network)


def _synthetic_mnist(n):
    """Class-separable 28x28 digit-ish data: class k lights a kxk block."""
    rs = np.random.RandomState(7)
    x = rs.rand(n, 1, 28, 28).astype("float32") * 0.1
    y = rs.randint(0, 10, n).astype("float32")
    for i in range(n):
        k = int(y[i])
        x[i, 0, 2:6 + k, 2:6 + k] += 0.9
    return x, y


def get_mnist_iter(args, kv):
    data_dir = getattr(args, "data_dir", None)
    if data_dir and os.path.exists(os.path.join(data_dir,
                                                "train-images-idx3-ubyte")):
        train = mx.io.MNISTIter(
            image=os.path.join(data_dir, "train-images-idx3-ubyte"),
            label=os.path.join(data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True)
        val = mx.io.MNISTIter(
            image=os.path.join(data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size)
        return train, val
    xtr, ytr = _synthetic_mnist(args.num_examples)
    xva, yva = _synthetic_mnist(1024)
    return (mx.io.NDArrayIter(xtr, ytr, args.batch_size, shuffle=True),
            mx.io.NDArrayIter(xva, yva, args.batch_size))


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=8192)
    parser.add_argument("--data-dir", type=str, default=None)
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=10, batch_size=128,
                        lr=0.05)
    args = parser.parse_args()

    sym = get_symbol(args.network, args.num_classes)
    fit.fit(args, sym, get_mnist_iter)
