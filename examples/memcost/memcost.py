#!/usr/bin/env python
"""Memory-cost study (reference ``example/memcost/``: measure the
training memory saved by gradient mirroring/recomputation).

TPU-native form: ask XLA itself — compile the fused train step under
each remat setting and read the program's activation (temp) memory from
``compiled.memory_analysis()``.  The measured story DIFFERS from the
reference's engine by design: XLA already plans conv-net memory, so on
ResNet-50 NO checkpoint policy reduces temp memory (full remat costs
+3%) — matching the README round-2 finding that mirroring is correctly
not the default here.  The win case is the transformer, where
``remat='dots_saveable'`` (save matmul outputs, recompute elementwise)
cuts activation memory ~23% (measured 5.9 GB -> 4.5 GB at
8L-d1024-T1024 bs8 on v5e).

    python examples/memcost/memcost.py --model resnet --batch 64
    python examples/memcost/memcost.py --model transformer
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def measure(remat, model, num_layers, batch, image,
            lm_layers=8, seq_len=1024, d_model=1024):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.fused import TrainStep
    from mxnet_tpu.models import resnet, transformer

    if model == "transformer":
        sym = transformer.get_symbol(vocab_size=8192,
                                     num_layers=lm_layers,
                                     d_model=d_model, num_heads=16,
                                     seq_len=seq_len)
        shapes = {"data": (batch, seq_len),
                  "softmax_label": (batch, seq_len)}
    else:
        sym = resnet.get_symbol(num_classes=1000,
                                num_layers=num_layers,
                                image_shape=(3, image, image))
        shapes = {"data": (batch, 3, image, image),
                  "softmax_label": (batch,)}
    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     compute_dtype="bfloat16",
                     remat=remat)
    params, aux, states = step.init_state(shapes)
    batch_dict = {k: jnp.zeros(v, "float32") for k, v in shapes.items()}
    lowered = step._jit_step.lower(params, aux, states, batch_dict,
                                  jax.random.PRNGKey(0), step.lr,
                                  jnp.asarray(1, "int32"))
    mem = lowered.compile().memory_analysis()
    return {
        "temp_mb": round(getattr(mem, "temp_size_in_bytes", 0) / 2**20,
                         1),
        "peak_mb": round((getattr(mem, "temp_size_in_bytes", 0)
                          + getattr(mem, "argument_size_in_bytes", 0)
                          + getattr(mem, "output_size_in_bytes", 0))
                         / 2**20, 1),
    }


def main(args):
    rows = []
    for name, remat in (("none", None), ("full", "full"),
                        ("dots_saveable", "dots_saveable")):
        m = measure(remat, args.model, args.num_layers, args.batch,
                    args.image, lm_layers=args.lm_layers,
                    seq_len=args.seq_len, d_model=args.d_model)
        rows.append((name, m))
        print("remat=%-14s temp(activations) %.1f MB  peak %.1f MB"
              % (name, m["temp_mb"], m["peak_mb"]))
    base = rows[0][1]["temp_mb"]
    best = min(rows[1:], key=lambda r: r[1]["temp_mb"])
    print("best policy %r saves %.0f%% of activation temp vs none "
          "(reference mirror: 30-50%% at ~5%% speed)"
          % (best[0], 100 * (1 - best[1]["temp_mb"] / max(base, 1e-9))))
    return rows


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=("resnet", "transformer"),
                   default="resnet")
    p.add_argument("--num-layers", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image", type=int, default=224)
    # transformer-config overrides (defaults = the measured v5e study;
    # CI shrinks them — the contract is policy coverage, not MBs)
    p.add_argument("--lm-layers", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=1024)
    main(p.parse_args())
