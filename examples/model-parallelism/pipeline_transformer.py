#!/usr/bin/env python
"""Pipeline-parallel transformer LM training through the public Module
API (first-class pipeline parallelism, round 4): the Symbol is cut into
heterogeneous stages (embed -> blocks -> head) by
``parallel.pipeline.split_symbol``, per-stage parameters/optimizer
states shard over the mesh's 'pipe' axis (each device holds ONLY its
stage), and the 1F1B schedule runs a bounded activation ring with
per-stage remat backward — O(S) activation memory, no gradient
collectives at all.

Runs on a virtual CPU mesh when real chips are scarce (the same code
drives a pod slice):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
        python examples/model-parallelism/pipeline_transformer.py

Reference analogue: the manual layer-per-GPU staging of
``example/model-parallel-lstm`` — here the cut, schedule, and sharding
are automatic.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

def main(args):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import create_mesh, mesh_scope
    from mxnet_tpu.parallel.pipeline import PipelineTrainStep

    n_dev = min(args.stages, len(jax.devices()))
    if n_dev < 2:
        print("need >= 2 devices for a pipeline; run with "
              "JAX_PLATFORMS=cpu XLA_FLAGS="
              "--xla_force_host_platform_device_count=%d" % args.stages)
        return 1

    sym = transformer.get_symbol(
        vocab_size=args.vocab, num_layers=args.layers, d_model=args.dim,
        num_heads=4, seq_len=args.seq_len,
        moe_experts=args.moe_experts, moe_top_k=2,
        moe_capacity_factor=float(max(args.moe_experts, 1)))

    rs = np.random.RandomState(0)
    toks = rs.randint(0, args.vocab,
                      (args.num_examples, args.seq_len)).astype("float32")
    labels = (3 * toks + 1) % args.vocab
    it = mx.io.NDArrayIter(toks, labels, batch_size=args.batch_size)

    mesh = create_mesh({"pipe": n_dev}, devices=jax.devices()[:n_dev])
    with mesh_scope(mesh):
        mod = mx.mod.Module(sym, context=mx.tpu(0),
                            pipeline_stages=n_dev,
                            pipeline_microbatches=args.microbatches,
                            pipeline_schedule=args.schedule)
        mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
                kvstore="dist_tpu_sync",
                optimizer_params={"learning_rate": args.lr},
                initializer=mx.init.Xavier(),
                eval_metric=mx.metric.Perplexity(ignore_label=None))
        assert isinstance(mod._fused, PipelineTrainStep)
        ppl = dict(mod.score(
            it, mx.metric.Perplexity(ignore_label=None)))["perplexity"]
    print("final perplexity: %.4f (%d stages, %s schedule%s)"
          % (ppl, n_dev, args.schedule,
             ", MoE E%d" % args.moe_experts if args.moe_experts else ""))
    if ppl < 3.0:
        print("PIPELINE TRAINS OK")
        return 0
    print("PIPELINE DID NOT LEARN")
    return 1


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="pipeline-parallel LM")
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--schedule", choices=("1f1b", "gpipe"),
                   default="1f1b")
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--vocab", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--moe-experts", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--num-examples", type=int, default=64)
    sys.exit(main(p.parse_args()))
