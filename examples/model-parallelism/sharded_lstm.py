#!/usr/bin/env python
"""Model parallelism, TPU-native (the re-design of
``example/model-parallel-lstm/lstm.py:65-129``).

The reference places each LSTM layer on a different GPU with
``group2ctx``/``AttrScope`` and pays a cross-device copy per boundary.
On TPU the same capability is expressed as *sharding*, not placement:
``param_sharding='tp'`` annotates weight shardings over the mesh's model
axis and XLA inserts the collectives over ICI.  Run on CPU with 8 virtual
devices to see the shardings:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/model-parallelism/sharded_lstm.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def build_lm(args):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=args.vocab,
                             output_dim=args.num_hidden, name="embed")
    # the whole stack is ONE fused lax.scan RNN op (reference FusedRNNCell
    # -> cuDNN; src/operator/rnn-inl.h)
    cell = mx.rnn.FusedRNNCell(args.num_hidden, num_layers=args.num_layers,
                               mode="lstm", prefix="lstm_")
    outputs, _ = cell.unroll(args.seq_len, inputs=embed, layout="NTC",
                             merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
    # "fc0" matches the tp rule table: column-parallel over 'model'
    pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab, name="fc0")
    label_f = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_f, name="softmax",
                                normalization="batch")


def main(args):
    import jax

    rs = np.random.RandomState(0)
    seqs = rs.randint(0, args.vocab,
                      (args.num_examples, args.seq_len)).astype("float32")
    nxt = np.roll(seqs, -1, axis=1)
    it = mx.io.NDArrayIter(seqs, nxt, args.batch_size, shuffle=True,
                           label_name="softmax_label")

    n_dev = len(jax.devices())
    model_axis = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    print("devices=%d -> mesh data=%d model=%d"
          % (n_dev, n_dev // model_axis, model_axis))

    from mxnet_tpu.parallel import create_mesh, mesh_scope
    import contextlib

    scope = contextlib.nullcontext()
    if model_axis > 1:
        # a hybrid data x model mesh: the 'model' axis carries the tensor-
        # parallel shards (reference group2ctx placed layers on devices;
        # here XLA lays collectives over the mesh axes)
        mesh = create_mesh({"data": n_dev // model_axis,
                            "model": model_axis})
        scope = mesh_scope(mesh)

    mod = mx.mod.Module(build_lm(args), context=mx.tpu())
    with scope:
        mod.fit(it, num_epoch=args.num_epochs,
                eval_metric=mx.metric.Perplexity(ignore_label=None),
                kvstore="dist_tpu_sync" if n_dev > 1 else "local",
                optimizer="adam",
                optimizer_params={"learning_rate": args.lr},
                initializer=mx.init.Xavier(),
                param_sharding="tp" if model_axis > 1 else None,
                batch_end_callback=mx.callback.Speedometer(
                    args.batch_size, 20))
    if model_axis > 1:
        specs = getattr(mod._fused, "_in_pshard", None)
        if specs is not None:
            print("parameter shardings:", specs)
    return mod


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--num-hidden", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-examples", type=int, default=2048)
    main(p.parse_args())
