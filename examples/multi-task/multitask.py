#!/usr/bin/env python
"""Multi-task training — one trunk, two supervised heads
(reference ``example/multi-task/example_multi_task.py``: shared conv
trunk, two SoftmaxOutput heads grouped, per-head metrics).

Synthetic task on 16x16 images of a bright blob: head A classifies the
QUADRANT (4-way), head B classifies the SIZE (small/large, 2-way) —
two labels per example, one shared representation.

    python examples/multi-task/multitask.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                           pad=(1, 1), name="conv1")
    c = mx.sym.Activation(mx.sym.BatchNorm(c, name="bn1"),
                          act_type="relu")
    c = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c = mx.sym.Convolution(c, num_filter=32, kernel=(3, 3), pad=(1, 1),
                           name="conv2")
    c = mx.sym.Activation(mx.sym.BatchNorm(c, name="bn2"),
                          act_type="relu")
    feat = mx.sym.Flatten(mx.sym.Pooling(c, global_pool=True,
                                         kernel=(2, 2),
                                         pool_type="avg"))
    quad = mx.sym.FullyConnected(feat, num_hidden=4, name="quad_fc")
    quad = mx.sym.SoftmaxOutput(quad, name="quad")
    size = mx.sym.FullyConnected(feat, num_hidden=2, name="size_fc")
    size = mx.sym.SoftmaxOutput(size, name="size")
    return mx.sym.Group([quad, size])


def synth(n, rs):
    imgs = 0.2 * rs.randn(n, 1, 16, 16).astype("float32")
    quad = rs.randint(0, 4, n).astype("float32")
    size = rs.randint(0, 2, n).astype("float32")
    yy, xx = np.mgrid[0:16, 0:16]
    for i in range(n):
        cy = 4 + 8 * (int(quad[i]) // 2)
        cx = 4 + 8 * (int(quad[i]) % 2)
        r2 = (2 if size[i] == 0 else 4) ** 2
        imgs[i, 0][((yy - cy) ** 2 + (xx - cx) ** 2) < r2] += 1.5
    return imgs, quad, size


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    imgs, quad, size = synth(args.num_examples, rs)
    it = mx.io.NDArrayIter(
        imgs, {"quad_label": quad, "size_label": size},
        batch_size=args.batch_size)
    mod = mx.mod.Module(get_symbol(),
                        label_names=("quad_label", "size_label"),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy())
    # per-head accuracies (update_metric pairs heads by exact name)
    accs = {}
    for name in ("quad", "size"):
        metric = mx.metric.Accuracy()
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=False)
            outs = mod.get_outputs()
            idx = 0 if name == "quad" else 1
            lab = batch.label[idx]
            metric.update([lab], [outs[idx]])
        accs[name] = metric.get()[1]
        print("%s accuracy %.4f" % (name, accs[name]))
    return accs


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=12)
    main(p.parse_args())
