#!/usr/bin/env python
"""Noise-contrastive estimation over a shared output-embedding table
(reference ``example/nce-loss/toy_nce.py`` / ``nce.py``): instead of a
full-vocabulary softmax — O(vocab) output FLOPs and a dense (vocab, h)
gradient per step — each example scores 1 true + K noise candidates
against the output embedding and trains a logistic discriminator
(``LogisticRegressionOutput``), touching only K+1 embedding rows.

Toy task: predict (a + b) mod vocab from tokens (a, b).  After NCE
training the FULL-vocab argmax over the learned output table must
recover the target (the point of NCE: cheap training, intact ranking).

    python examples/nce-loss/toy_nce.py --num-epochs 12
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def nce_loss(data, label, label_weight, vocab_size, num_hidden,
             num_label):
    """The reference's NCE head (``nce-loss/nce.py:26-33``): candidate
    embeddings dot the feature vector, logistic loss over true/noise."""
    embed_weight = mx.sym.Variable("output_embed_weight")
    label_embed = mx.sym.Embedding(label, input_dim=vocab_size,
                                   weight=embed_weight,
                                   output_dim=num_hidden,
                                   name="label_embed")
    data = mx.sym.Reshape(data, shape=(-1, 1, num_hidden))
    pred = mx.sym.broadcast_mul(data, label_embed)
    pred = mx.sym.sum(pred, axis=2)
    return mx.sym.LogisticRegressionOutput(pred, label_weight,
                                           name="nce")


def get_symbol(vocab_in, vocab_out, num_hidden, num_label):
    data = mx.sym.Variable("data")          # (N, 2) token pair
    label = mx.sym.Variable("label")        # (N, K+1) candidates
    label_weight = mx.sym.Variable("label_weight")  # 1 true, 0 noise
    emb = mx.sym.Embedding(data, input_dim=vocab_in, output_dim=num_hidden,
                           name="data_embed")
    feat = mx.sym.Reshape(emb, shape=(-1, 2 * num_hidden))
    feat = mx.sym.FullyConnected(feat, num_hidden=num_hidden,
                                 name="feat_fc")
    feat = mx.sym.Activation(feat, act_type="tanh")
    return nce_loss(feat, label, label_weight, vocab_out, num_hidden,
                    num_label)


def make_batches(n, vocab, num_label, rs):
    a = rs.randint(0, vocab, n)
    b = rs.randint(0, vocab, n)
    y = (a + b) % vocab
    data = np.stack([a, b], 1).astype("float32")
    # candidate 0 is the true class; the rest are noise draws
    cands = np.empty((n, num_label), "float32")
    weights = np.zeros((n, num_label), "float32")
    cands[:, 0] = y
    weights[:, 0] = 1.0
    cands[:, 1:] = rs.randint(0, vocab, (n, num_label - 1))
    return data, y, cands, weights


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    vocab, h, num_label = args.vocab, args.num_hidden, args.num_label
    data, y, cands, weights = make_batches(args.num_examples, vocab,
                                           num_label, rs)
    it = mx.io.NDArrayIter({"data": data, "label": cands},
                           {"label_weight": weights},
                           batch_size=args.batch_size)
    net = get_symbol(vocab, vocab, h, num_label)
    mod = mx.mod.Module(net, data_names=("data", "label"),
                        label_names=("label_weight",),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss())

    # full-vocab ranking with the learned tables: NCE must have shaped
    # the output embedding so the true class wins the argmax
    params, _ = mod.get_params()
    emb_w = params["data_embed_weight"].asnumpy()
    fc_w = params["feat_fc_weight"].asnumpy()
    fc_b = params["feat_fc_bias"].asnumpy()
    out_w = params["output_embed_weight"].asnumpy()
    feats = np.concatenate([emb_w[data[:, 0].astype(int)],
                            emb_w[data[:, 1].astype(int)]], 1)
    hid = np.tanh(feats @ fc_w.T + fc_b)
    scores = hid @ out_w.T            # (N, vocab) full ranking
    acc = float((scores.argmax(1) == y).mean())
    print("full-vocab argmax accuracy %.4f (vocab=%d, %d candidates "
          "scored per step during training)" % (acc, vocab, num_label))
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=30)
    p.add_argument("--num-hidden", type=int, default=96)
    p.add_argument("--num-label", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-epochs", type=int, default=25)
    p.add_argument("--num-examples", type=int, default=8192)
    main(p.parse_args())
