#!/usr/bin/env python
"""Neural style transfer — optimizing the INPUT image
(reference ``example/neural-style/``: content + Gram-matrix style
losses over fixed conv features; gradient descent on the image, not
the weights).

The capability this proves: ``autograd.mark_variables`` on a non-
parameter input, backward producing input gradients, and an update
loop where every network weight is frozen.

    python examples/neural-style/neural_style.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd


def features(img, weights):
    """Two conv feature maps from a fixed random 'perception' net (the
    reference uses VGG19 relu layers; random filters preserve the
    texture statistics the Gram loss needs)."""
    w1, w2 = weights
    f1 = mx.nd.Activation(
        mx.nd.Convolution(img, w1, kernel=(3, 3), pad=(1, 1),
                          num_filter=w1.shape[0], no_bias=True),
        act_type="relu")
    f2 = mx.nd.Activation(
        mx.nd.Convolution(f1, w2, kernel=(3, 3), pad=(1, 1),
                          num_filter=w2.shape[0], no_bias=True),
        act_type="relu")
    return f1, f2


def gram(f):
    n, c = f.shape[0], f.shape[1]
    flat = mx.nd.Reshape(f, shape=(n, c, -1))
    hw = flat.shape[2]
    return mx.nd.batch_dot(flat, flat, transpose_b=True) / float(hw)


def main(args):
    rs = np.random.RandomState(0)
    size = args.size
    # content: diagonal gradient image; style: checkerboard texture
    yy, xx = np.mgrid[0:size, 0:size].astype("float32")
    content = ((yy + xx) / (2 * size))[None, None].repeat(3, 1)
    style = (((yy // 4 + xx // 4) % 2)[None, None]
             .repeat(3, 1).astype("float32"))
    content_nd = mx.nd.array(content)
    style_nd = mx.nd.array(style)

    weights = (mx.nd.array(rs.randn(8, 3, 3, 3).astype("float32") * 0.4),
               mx.nd.array(rs.randn(16, 8, 3, 3).astype("float32") * 0.2))

    with autograd.pause():
        cf1, cf2 = features(content_nd, weights)
        sf1, sf2 = features(style_nd, weights)
        sg1, sg2 = gram(sf1), gram(sf2)

    img = mx.nd.array(content + 0.2 * rs.randn(*content.shape)
                      .astype("float32"))
    img_grad = mx.nd.zeros(img.shape)
    autograd.mark_variables([img], [img_grad])

    first = last = None
    for it in range(args.iters):
        with autograd.record():
            f1, f2 = features(img, weights)
            closs = mx.nd.mean(mx.nd.square(f2 - cf2))
            g1, g2 = gram(f1), gram(f2)
            sloss = (mx.nd.mean(mx.nd.square(g1 - sg1))
                     + mx.nd.mean(mx.nd.square(g2 - sg2)))
            loss = closs + args.style_weight * sloss
        autograd.backward([loss])
        # gradient descent ON THE IMAGE; weights never move
        img_np = img.asnumpy() - args.lr * img_grad.asnumpy()
        img._set_data(mx.nd.array(np.clip(img_np, -1.5, 2.5))._data)
        val = float(loss.asscalar())
        if first is None:
            first = val
        last = val
        if it % 10 == 0:
            print("iter %d loss %.5f (content %.5f style %.5f)"
                  % (it, val, float(closs.asscalar()),
                     float(sloss.asscalar())))
    print("loss %.5f -> %.5f (%.1f%% reduction)"
          % (first, last, 100 * (1 - last / first)))
    return first, last


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--iters", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--style-weight", type=float, default=1.0)
    main(p.parse_args())
