#!/usr/bin/env python
"""Custom operator written in NUMPY (reference ``example/numpy-ops/
custom_softmax.py``): a user-defined softmax-loss op whose forward AND
backward are plain numpy, registered through ``mx.operator.CustomOp``/
``CustomOpProp`` and trained inside a symbolic graph via
``mx.sym.Custom``.

The numpy tier runs host-side through ``pure_callback``
(``MXNET_CUSTOM_OP_CALLBACK=1`` forces it; device-traceable ops written
with ``mx.nd`` stay on-chip — see ``examples/torch``).  Training must
reach >0.95 accuracy, proving gradients flow through the host-side op.

    python examples/numpy-ops/numpy_softmax.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")  # host callbacks need cpu

import mxnet_tpu as mx


class NumpySoftmax(mx.operator.CustomOp):
    """Softmax + cross-entropy head, forward/backward in numpy
    (reference ``custom_softmax.py`` shape)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        y = e / e.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        label = in_data[1].asnumpy().astype(np.int32)
        y = out_data[0].asnumpy().copy()
        y[np.arange(len(label)), label] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y / len(label)))


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main(args):
    rs = np.random.RandomState(0)
    centers = rs.randn(3, 16).astype("float32") * 2.0
    y = rs.randint(0, 3, args.num_examples).astype("float32")
    X = centers[y.astype(int)] + 0.5 * rs.randn(args.num_examples,
                                                16).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=64,
                           label_name="softmax_label")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.Custom(fc, label, op_type="numpy_softmax",
                        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    score = dict(mod.score(it, mx.metric.Accuracy()))
    print("numpy-op accuracy %.4f" % score["accuracy"])
    return score["accuracy"]


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=30)
    main(p.parse_args())
