#!/usr/bin/env python
"""Profile one ResNet train step to a chrome trace (reference
``example/profiler/profiler_executor.py``; our profiler wraps
``jax.profiler``, see ``mxnet_tpu/profiler.py``).

    python examples/profiler/profile_resnet.py --out /tmp/mxnet_profile
    # then open the trace in Perfetto / chrome://tracing
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def main(args):
    from mxnet_tpu.models import resnet
    from mxnet_tpu.fused import TrainStep

    sym = resnet.get_symbol(num_classes=100, num_layers=args.num_layers,
                            image_shape=(3, 32, 32))
    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1})
    shapes = {"data": (args.batch_size, 3, 32, 32),
              "softmax_label": (args.batch_size,)}
    params, aux, states = step.init_state(shapes)
    import jax

    rng = jax.random.PRNGKey(0)
    batch = {"data": jax.numpy.asarray(
                 np.random.rand(*shapes["data"]).astype("float32")),
             "softmax_label": jax.numpy.zeros(shapes["softmax_label"],
                                              "float32")}
    # warm up (compile) outside the profile window
    params, aux, states, _ = step(params, aux, states, batch, rng)

    mx.profiler.profiler_set_config(mode="all", filename=args.out)
    mx.profiler.profiler_set_state("run")
    for _ in range(args.iters):
        params, aux, states, out = step(params, aux, states, batch, rng)
    float(np.asarray(out[0][0, 0]))  # drain the device
    mx.profiler.profiler_set_state("stop")
    print("trace written under", args.out)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=20)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--out", type=str, default="/tmp/mxnet_profile")
    main(p.parse_args())
