#!/usr/bin/env python
"""Train a miniature Faster R-CNN / R-FCN detector end-to-end
(reference ``example/rcnn``): an RPN over a small conv backbone feeds
the ``Proposal`` op, proposals drive ``PSROIPooling`` (the R-FCN head),
and — like the reference, whose target assignment runs as custom Python
ops — anchor and proposal targets are ``CustomOp``s written with
``mx.nd`` operations, which this framework traces into the XLA program
so they run ON the accelerator (no host callback).

Hermetic: synthetic images with one colored square per class, gt boxes
in pixel coordinates (the Proposal/R-CNN convention).

    python examples/rcnn/train_rcnn.py --num-epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
import mxnet_tpu.operator as mxop

logging.basicConfig(level=logging.INFO)

NUM_CLASSES = 2          # foreground classes; 0 is background
IMG = 32
STRIDE = 4
FM = IMG // STRIDE       # 8x8 feature map
SCALES = (2.0, 4.0)      # anchor sizes 8, 16 px at stride 4
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
POST_NMS = 8             # rois per image
POOLED = 3               # psroi grid


def _base_anchors():
    """Same anchor construction as the Proposal op (pixel coords)."""
    base = []
    for r in RATIOS:
        for s in SCALES:
            ww = STRIDE * s * np.sqrt(1.0 / r)
            hh = STRIDE * s * np.sqrt(r)
            base.append((-ww / 2, -hh / 2, ww / 2, hh / 2))
    base = np.asarray(base, "float32")                      # (A, 4)
    sy = np.arange(FM, dtype="float32") * STRIDE
    sx = np.arange(FM, dtype="float32") * STRIDE
    cy, cx = np.meshgrid(sy, sx, indexing="ij")
    shift = np.stack([cx, cy, cx, cy], axis=-1)             # (H, W, 4)
    return (shift[:, :, None, :] + base[None, None, :, :]    # (H,W,A,4)
            ).reshape(-1, 4)                                 # (HWA, 4)


def _iou_nd(boxes, gt):
    """IoU of (N, 4) boxes vs (N, 4) gt rows — mx.nd, traceable."""
    x1 = mx.nd.elemwise_maximum(boxes[:, 0], gt[:, 0])
    y1 = mx.nd.elemwise_maximum(boxes[:, 1], gt[:, 1])
    x2 = mx.nd.elemwise_minimum(boxes[:, 2], gt[:, 2])
    y2 = mx.nd.elemwise_minimum(boxes[:, 3], gt[:, 3])
    iw = mx.nd._maximum_scalar(x2 - x1 + 1.0, scalar=0.0)
    ih = mx.nd._maximum_scalar(y2 - y1 + 1.0, scalar=0.0)
    inter = iw * ih
    area_b = (boxes[:, 2] - boxes[:, 0] + 1.0) * \
             (boxes[:, 3] - boxes[:, 1] + 1.0)
    area_g = (gt[:, 2] - gt[:, 0] + 1.0) * (gt[:, 3] - gt[:, 1] + 1.0)
    return inter / (area_b + area_g - inter + 1e-6)


class AnchorTarget(mxop.CustomOp):
    """RPN targets (reference ``example/rcnn`` AnchorTarget layer, run
    as a custom op): fg/bg labels by IoU vs the (single) gt box, bbox
    regression deltas for fg anchors.  One gt per image keeps the demo
    hermetic."""

    def forward(self, is_train, req, in_data, out_data, aux):
        gt = in_data[0]                       # (B, 1, 5) [cls,x1,y1,x2,y2]
        b = gt.shape[0]
        anchors = mx.nd.array(_base_anchors())            # (HWA, 4)
        n = anchors.shape[0]
        labels, targets, masks = [], [], []
        for i in range(b):                    # B is tiny and static
            g = mx.nd.tile(mx.nd.Reshape(gt[i, 0, 1:], shape=(1, 4)),
                           reps=(n, 1))
            iou = _iou_nd(anchors, g)
            fg = iou > 0.5
            bg = iou < 0.2
            lab = mx.nd.where(fg, mx.nd.ones((n,)),
                              mx.nd.where(bg, mx.nd.zeros((n,)),
                                          mx.nd.full((n,), -1.0)))
            aw = anchors[:, 2] - anchors[:, 0] + 1.0
            ah = anchors[:, 3] - anchors[:, 1] + 1.0
            acx = anchors[:, 0] + aw * 0.5
            acy = anchors[:, 1] + ah * 0.5
            gw = g[:, 2] - g[:, 0] + 1.0
            gh = g[:, 3] - g[:, 1] + 1.0
            gcx = g[:, 0] + gw * 0.5
            gcy = g[:, 1] + gh * 0.5
            dx = (gcx - acx) / aw
            dy = (gcy - acy) / ah
            dw = mx.nd.log(gw / aw)
            dh = mx.nd.log(gh / ah)
            tgt = mx.nd.stack(dx, dy, dw, dh, axis=1)      # (HWA, 4)
            m = mx.nd.Reshape(fg.astype("float32"), shape=(n, 1))
            labels.append(lab)
            targets.append(tgt * m)
            masks.append(mx.nd.tile(m, reps=(1, 4)))
        self.assign(out_data[0], req[0], mx.nd.stack(*labels, axis=0))
        self.assign(out_data[1], req[1], mx.nd.stack(*targets, axis=0))
        self.assign(out_data[2], req[2], mx.nd.stack(*masks, axis=0))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    mx.nd.zeros_like(in_data[0]))


@mxop.register("rcnn_anchor_target")
class AnchorTargetProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["gt"]

    def list_outputs(self):
        return ["label", "bbox_target", "bbox_mask"]

    def infer_shape(self, in_shape):
        b = in_shape[0][0]
        n = FM * FM * A
        return [in_shape[0]], [(b, n), (b, n, 4), (b, n, 4)], []

    def create_operator(self, ctx, shapes, dtypes):
        return AnchorTarget()


class ProposalTarget(mxop.CustomOp):
    """Per-ROI class targets (reference proposal_target custom op):
    gt class + 1 when IoU > 0.5, else background 0."""

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0]                     # (B*P, 5) [bidx,x1,y1,x2,y2]
        gt = in_data[1]                       # (B, 1, 5)
        bidx = rois[:, 0].astype("int32")
        g = mx.nd.take(mx.nd.Reshape(gt, shape=(-3, 0)), bidx)  # (BP, 5)
        iou = _iou_nd(rois[:, 1:], g[:, 1:])
        lab = mx.nd.where(iou > 0.5, g[:, 0] + 1.0,
                          mx.nd.zeros_like(iou))
        self.assign(out_data[0], req[0], lab)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], mx.nd.zeros_like(in_data[0]))
        self.assign(in_grad[1], req[1], mx.nd.zeros_like(in_data[1]))


@mxop.register("rcnn_proposal_target")
class ProposalTargetProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt"]

    def list_outputs(self):
        return ["label"]

    def infer_shape(self, in_shape):
        return [in_shape[0], in_shape[1]], [(in_shape[0][0],)], []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget()


def conv_block(data, num_filter, name, stride=(1, 1)):
    c = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                           stride=stride, num_filter=num_filter,
                           no_bias=True, name=name)
    bn = mx.sym.BatchNorm(c, fix_gamma=False, name=name + "_bn")
    return mx.sym.Activation(bn, act_type="relu")


def rcnn_symbol(batch_size):
    data = mx.sym.Variable("data")
    gt = mx.sym.Variable("label")             # (B, 1, 5) pixel coords
    im_info = mx.sym.Variable("im_info")      # (B, 3) [h, w, scale]

    body = conv_block(data, 16, "c1", stride=(2, 2))     # 32 -> 16
    body = conv_block(body, 32, "c2", stride=(2, 2))     # -> 8 (stride 4)

    # ---- RPN ----
    rpn = conv_block(body, 32, "rpn_conv")
    rpn_cls = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A,
                                 name="rpn_cls")          # (B, 2A, H, W)
    rpn_bbox = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A,
                                  name="rpn_bbox")        # (B, 4A, H, W)

    tgt = mx.sym.Custom(gt, op_type="rcnn_anchor_target", name="atgt")
    rpn_label, bb_target, bb_mask = tgt[0], tgt[1], tgt[2]

    # fg/bg softmax over the 2-way axis; layout (B, 2, A*H*W) with the
    # anchor axis enumerated (H, W, A) row-major to match AnchorTarget
    cls_for_loss = mx.sym.Reshape(
        mx.sym.transpose(mx.sym.Reshape(rpn_cls,
                                        shape=(0, 2, A, FM, FM)),
                         axes=(0, 1, 3, 4, 2)),
        shape=(0, 2, -1), name="rpn_cls_hwa")
    rpn_cls_loss = mx.sym.SoftmaxOutput(
        cls_for_loss, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")

    bb_pred = mx.sym.Reshape(
        mx.sym.transpose(mx.sym.Reshape(rpn_bbox,
                                        shape=(0, A, 4, FM, FM)),
                         axes=(0, 3, 4, 1, 2)),
        shape=(0, -1, 4), name="rpn_bb_hwa")              # (B, HWA, 4)
    rpn_bbox_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(bb_mask * (bb_pred - bb_target), scalar=3.0),
        grad_scale=1.0 / (FM * FM * A), name="rpn_bbox_loss")

    # ---- proposals (gradient-free, like the reference) ----
    rpn_prob = mx.sym.Reshape(
        mx.sym.softmax(mx.sym.Reshape(rpn_cls, shape=(0, 2, -1)),
                       axis=1),
        shape=(0, 2 * A, FM, FM), name="rpn_prob")
    rois = mx.sym.Proposal(
        mx.sym.BlockGrad(rpn_prob), mx.sym.BlockGrad(rpn_bbox),
        im_info, feature_stride=STRIDE, scales=SCALES, ratios=RATIOS,
        rpn_pre_nms_top_n=32, rpn_post_nms_top_n=POST_NMS,
        threshold=0.7, rpn_min_size=4, name="proposal")
    rois_flat = mx.sym.Reshape(rois, shape=(-3, 0), name="rois_flat")

    # ---- R-FCN head: position-sensitive score maps + PSROIPooling ----
    psroi_feat = mx.sym.Convolution(
        body, kernel=(1, 1),
        num_filter=(NUM_CLASSES + 1) * POOLED * POOLED, name="psconv")
    pooled = mx.sym.PSROIPooling(
        psroi_feat, mx.sym.BlockGrad(rois_flat),
        spatial_scale=1.0 / STRIDE, output_dim=NUM_CLASSES + 1,
        pooled_size=POOLED, group_size=POOLED, name="psroi")
    scores = mx.sym.Reshape(
        mx.sym.Pooling(pooled, global_pool=True, pool_type="avg",
                       kernel=(1, 1)),
        shape=(0, NUM_CLASSES + 1), name="roi_scores")

    roi_label = mx.sym.Custom(mx.sym.BlockGrad(rois_flat), gt,
                              op_type="rcnn_proposal_target",
                              name="ptgt")
    roi_cls_loss = mx.sym.SoftmaxOutput(
        scores, roi_label, normalization="valid", name="roi_cls_prob")

    return mx.sym.Group([rpn_cls_loss, rpn_bbox_loss, roi_cls_loss,
                         mx.sym.BlockGrad(rois),
                         mx.sym.BlockGrad(roi_label)])


def synthetic_batch(rs, n):
    imgs = np.zeros((n, 3, IMG, IMG), "float32")
    labels = np.zeros((n, 1, 5), "float32")
    for i in range(n):
        cls = int(rs.randint(NUM_CLASSES))
        w = int(rs.randint(8, 17))
        x0 = int(rs.randint(0, IMG - w))
        y0 = int(rs.randint(0, IMG - w))
        imgs[i, cls, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0, y0, x0 + w - 1, y0 + w - 1]
    return imgs, labels


def main(args):
    rs = np.random.RandomState(0)
    imgs, labels = synthetic_batch(rs, args.num_examples)
    im_info = np.tile(np.asarray([[IMG, IMG, 1.0]], "float32"),
                      (args.num_examples, 1))
    it = mx.io.NDArrayIter({"data": imgs, "im_info": im_info},
                           {"label": labels}, args.batch_size,
                           shuffle=True)

    sym = rcnn_symbol(args.batch_size)
    mod = mx.mod.Module(sym, context=mx.tpu(),
                        data_names=("data", "im_info"),
                        label_names=("label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        tot_roi = acc_n = acc_c = 0.0
        nb = 0
        for batch in it:
            mod.forward(batch, is_train=True)
            _, _, roi_prob, rois, roi_label = mod.get_outputs()
            mod.backward()
            mod.update()
            p = roi_prob.asnumpy()
            rl = roi_label.asnumpy().astype("int64")
            picked = p[np.arange(p.shape[0]), rl]
            tot_roi += float(-np.log(np.maximum(picked, 1e-8)).mean())
            acc_c += float((p.argmax(axis=1) == rl).sum())
            acc_n += rl.shape[0]
            nb += 1
        roi_loss = tot_roi / nb
        roi_acc = acc_c / acc_n
        if first is None:
            first = roi_loss
        last = roi_loss
        logging.info("Epoch[%d] roi-loss=%.4f roi-acc=%.3f", epoch,
                     roi_loss, roi_acc)
    print("loss first->last: %.4f -> %.4f" % (first, last))
    print("final roi accuracy: %.3f" % roi_acc)
    if last < first and roi_acc > 0.6:
        print("RCNN TRAINS OK")
    else:
        print("RCNN DID NOT LEARN")
        return 1
    return 0


if __name__ == "__main__":
    p = argparse.ArgumentParser(description="train mini Faster R-CNN")
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--num-examples", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--lr", type=float, default=2e-3)
    sys.exit(main(p.parse_args()))
