#!/usr/bin/env python
"""Matrix factorization recommender over row_sparse embedding tables
(reference ``example/recommenders/matrix_fact.py`` / ``demo1-MF.ipynb``:
user/item Embedding -> dot -> regression on ratings; RMSE metric).

This is the workload the sparse machinery exists for (reference
``src/kvstore/kvstore_dist.h:346-385`` sparse pull): embedding tables
large enough that moving WHOLE tables per step is waste.  Each batch

* pulls ONLY the touched user/item rows (``kvstore.row_sparse_pull``),
* computes the MF prediction and per-row gradients on device,
* pushes ``row_sparse`` gradients (unique-row aggregated), and
* updates through ``sparse.sgd_update`` — a row-slice update, never a
  full-table write.

Per-batch unique-row counts vary organically, so every batch has a
different nnz; ``MXNET_SPARSE_NNZ_BUCKETS=1`` pads nnz to power-of-two
buckets, bounding recompiles at O(log max_nnz) instead of one
executable per distinct count (``--nnz-buckets``).

    python examples/recommenders/matrix_fact.py --num-epochs 4
    python examples/recommenders/matrix_fact.py --nnz-buckets --bench
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def synthetic_movielens(num_users, num_items, num_ratings, factors, rs):
    """Latent-factor ratings with noise, clipped to the 1-5 star range
    (MovieLens-shaped: long-tail item popularity)."""
    u_lat = rs.randn(num_users, factors).astype("float32") * 0.5
    i_lat = rs.randn(num_items, factors).astype("float32") * 0.5
    u_bias = rs.randn(num_users).astype("float32") * 0.3
    i_bias = rs.randn(num_items).astype("float32") * 0.3
    uids = rs.randint(0, num_users, num_ratings)
    # zipf-ish item popularity (long tail, like real catalogs)
    ranks = rs.zipf(1.3, num_ratings) % num_items
    iids = ranks.astype(np.int64)
    r = (3.0 + (u_lat[uids] * i_lat[iids]).sum(1)
         + u_bias[uids] + i_bias[iids]
         + 0.3 * rs.randn(num_ratings).astype("float32"))
    return uids, iids, np.clip(r, 1.0, 5.0).astype("float32")


def main(args):
    if args.nnz_buckets:
        os.environ["MXNET_SPARSE_NNZ_BUCKETS"] = "1"
    rs = np.random.RandomState(0)
    U, I, K = args.num_users, args.num_items, args.factors
    uids, iids, ratings = synthetic_movielens(U, I, args.num_ratings, K,
                                              rs)
    n_train = int(len(ratings) * 0.9)
    mean_r = float(ratings[:n_train].mean())

    kv = mx.kv.create("local")
    kv.init("user_emb", mx.nd.array(rs.randn(U, K).astype("float32")
                                    * 0.05))
    kv.init("item_emb", mx.nd.array(rs.randn(I, K).astype("float32")
                                    * 0.05))
    kv.init("user_bias", mx.nd.zeros((U, 1)))
    kv.init("item_bias", mx.nd.zeros((I, 1)))
    lr, wd = args.lr, args.wd

    def updater(key, grad, weight):
        # row-slice update: only the pushed rows are touched
        if isinstance(grad, sparse.RowSparseNDArray):
            sparse.sgd_update(weight, grad, lr=lr, wd=wd)
        else:
            weight.__isub__(grad * lr)

    kv._set_updater(updater)

    shapes_seen = set()

    def pull_rows(name, shape1, row_ids):
        out = sparse.zeros("row_sparse", shape1)
        kv.row_sparse_pull(name, out=out,
                           row_ids=mx.nd.array(row_ids))
        shapes_seen.add((name, out._data.shape[0]))
        return out.data.asnumpy()

    def run_epoch(lo, hi, train):
        sq_err, count = 0.0, 0
        for b in range(lo, hi, args.batch_size):
            ub = uids[b:b + args.batch_size]
            ib = iids[b:b + args.batch_size]
            rb = ratings[b:b + args.batch_size]
            u_unique, u_pos = np.unique(ub, return_inverse=True)
            i_unique, i_pos = np.unique(ib, return_inverse=True)
            ue_rows = pull_rows("user_emb", (U, K), u_unique)
            ie_rows = pull_rows("item_emb", (I, K), i_unique)
            ub_rows = pull_rows("user_bias", (U, 1), u_unique)
            ib_rows = pull_rows("item_bias", (I, 1), i_unique)

            ue, ie = ue_rows[u_pos], ie_rows[i_pos]
            pred = ((ue * ie).sum(1) + ub_rows[u_pos, 0]
                    + ib_rows[i_pos, 0] + mean_r)
            err = pred - rb
            sq_err += float((err * err).sum())
            count += len(rb)
            if not train:
                continue
            # unique-row aggregated gradients (mean per touched row —
            # each row's update is independent of how often other rows
            # appear in the batch), pushed row_sparse
            cu = np.bincount(u_pos).astype("float32")[:, None]
            ci = np.bincount(i_pos).astype("float32")[:, None]
            gu = np.zeros_like(ue_rows)
            np.add.at(gu, u_pos, err[:, None] * ie)
            gu /= cu
            gi = np.zeros_like(ie_rows)
            np.add.at(gi, i_pos, err[:, None] * ue)
            gi /= ci
            gub = np.zeros_like(ub_rows)
            np.add.at(gub, u_pos, err[:, None])
            gub /= cu
            gib = np.zeros_like(ib_rows)
            np.add.at(gib, i_pos, err[:, None])
            gib /= ci
            for name, g, idx, shape1 in (
                    ("user_emb", gu, u_unique, (U, K)),
                    ("item_emb", gi, i_unique, (I, K)),
                    ("user_bias", gub, u_unique, (U, 1)),
                    ("item_bias", gib, i_unique, (I, 1))):
                rsp = sparse.row_sparse_array(
                    (g, idx.astype(np.int64)), shape=shape1)
                shapes_seen.add((name + "_g", rsp._data.shape[0]))
                kv.push(name, rsp)
        return (sq_err / max(count, 1)) ** 0.5

    t0 = time.perf_counter()
    rmse = val_rmse = float("inf")
    for epoch in range(args.num_epochs):
        rmse = run_epoch(0, n_train, train=True)
        val_rmse = run_epoch(n_train, len(ratings), train=False)
        print("epoch %d train-rmse %.4f val-rmse %.4f"
              % (epoch, rmse, val_rmse))
    dt = time.perf_counter() - t0
    total = args.num_epochs * len(ratings)
    result = {
        "metric": "mf_ratings_per_sec",
        "value": round(total / dt, 1),
        "unit": "ratings/s",
        "users": U, "items": I, "factors": K,
        "val_rmse": round(val_rmse, 4),
        "distinct_sparse_shapes": len(shapes_seen),
        "nnz_buckets": bool(args.nnz_buckets),
    }
    if args.bench:
        print(json.dumps(result))
    else:
        print("ratings/s %.1f | distinct sparse component shapes "
              "(≈ kernel compiles): %d | buckets=%s"
              % (result["value"], len(shapes_seen),
                 bool(args.nnz_buckets)))
    return val_rmse


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-users", type=int, default=10000)
    p.add_argument("--num-items", type=int, default=5000)
    p.add_argument("--num-ratings", type=int, default=100000)
    p.add_argument("--factors", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.3)
    p.add_argument("--wd", type=float, default=1e-5)
    p.add_argument("--nnz-buckets", action="store_true",
                   help="MXNET_SPARSE_NNZ_BUCKETS=1: bound recompiles "
                        "at O(log max_nnz)")
    p.add_argument("--bench", action="store_true",
                   help="print one JSON line with ratings/s")
    main(p.parse_args())
