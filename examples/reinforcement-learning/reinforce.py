#!/usr/bin/env python
"""REINFORCE policy gradient with imperative autograd rollouts
(reference ``example/reinforcement-learning/`` — the imperative
train-loop pattern of ``parallel_actor_critic``/``dqn``: per-step
stochastic policy forwards, trajectory collection, one backward over
the whole episode batch).

Environment: an 8-state chain walk; the agent starts at 0, the goal is
state 7, actions move left/right, reward 1.0 only at the goal.  The
policy must learn 'always right' from reward alone.

Exercises what Module.fit cannot: many recorded forwards per backward
(one per env step), data-dependent episode dynamics on the host, loss
assembled imperatively from sampled actions and discounted returns.

    python examples/reinforcement-learning/reinforce.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd


class ChainEnv:
    """Vectorized 8-state chain: actions 0=left 1=right; reward at the
    terminal goal state."""

    def __init__(self, n_envs, n_states=8, horizon=10):
        self.n_envs, self.n_states, self.horizon = n_envs, n_states, \
            horizon

    def rollout(self, policy_fn, rs):
        pos = np.zeros(self.n_envs, dtype=np.int64)
        done = np.zeros(self.n_envs, dtype=bool)
        logps, rewards, masks = [], [], []
        for _t in range(self.horizon):
            obs = np.eye(self.n_states, dtype="float32")[pos]
            logp_all = policy_fn(mx.nd.array(obs))       # (N, 2) log pi
            probs = np.exp(logp_all.asnumpy())
            acts = (rs.rand(self.n_envs) < probs[:, 1]).astype(np.int64)
            # recorded gather of the sampled action's log-prob
            onehot = np.eye(2, dtype="float32")[acts]
            logp = mx.nd.sum(logp_all * mx.nd.array(onehot), axis=1)
            step = np.where(acts == 1, 1, -1)
            pos = np.clip(np.where(done, pos, pos + step), 0,
                          self.n_states - 1)
            reached = (pos == self.n_states - 1) & ~done
            rewards.append(reached.astype("float32"))
            masks.append((~done).astype("float32"))
            done = done | reached
            logps.append(logp)
        return logps, rewards, masks, done


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    env = ChainEnv(args.n_envs)
    w1 = mx.nd.array(rs.randn(16, env.n_states).astype("float32") * 0.3)
    b1 = mx.nd.zeros((16,))
    w2 = mx.nd.array(rs.randn(2, 16).astype("float32") * 0.3)
    b2 = mx.nd.zeros((2,))
    params = [w1, b1, w2, b2]
    grads = [mx.nd.zeros(p.shape) for p in params]
    autograd.mark_variables(params, grads)

    def policy_fn(obs):
        h = mx.nd.Activation(
            mx.nd.FullyConnected(obs, w1, b1, num_hidden=16),
            act_type="tanh")
        logits = mx.nd.FullyConnected(h, w2, b2, num_hidden=2)
        return mx.nd.log_softmax(logits, axis=-1)

    mean_reward = 0.0
    for it in range(args.iters):
        with autograd.record():
            logps, rewards, masks, _done = env.rollout(policy_fn, rs)
            # discounted returns, then the REINFORCE surrogate
            returns = []
            g = np.zeros(args.n_envs, "float32")
            for r in reversed(rewards):
                g = r + args.gamma * g
                returns.insert(0, g.copy())
            base = np.mean([r.mean() for r in returns])
            loss = None
            for logp, g_t, m in zip(logps, returns, masks):
                adv = mx.nd.array((g_t - base) * m)
                term = mx.nd.sum(-logp * adv)
                loss = term if loss is None else loss + term
        autograd.backward([loss])
        for p, g in zip(params, grads):
            mx.nd.sgd_update(p, g, out=p, lr=args.lr,
                             rescale_grad=1.0 / args.n_envs)
        mean_reward = float(np.sum(rewards) / args.n_envs)
        if it % 10 == 0:
            print("iter %d mean-episode-reward %.3f" % (it, mean_reward))
    print("final mean-episode-reward %.3f" % mean_reward)
    return mean_reward


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--n-envs", type=int, default=64)
    p.add_argument("--iters", type=int, default=80)
    p.add_argument("--gamma", type=float, default=0.95)
    p.add_argument("--lr", type=float, default=0.05)
    main(p.parse_args())
