#!/usr/bin/env python
"""LSTM language model with bucketing (reference
``example/rnn/lstm_bucketing.py``): ``BucketSentenceIter`` feeds
variable-length sequences to a ``BucketingModule`` whose per-bucket graphs
(one XLA compile per bucket shape) share parameters.

Uses PTB text if ``--data-dir`` has the files; otherwise a synthetic
corpus with learnable next-token structure.

    python examples/rnn/lstm_bucketing.py --num-epochs 5
"""
import argparse
import logging
import os
import sys

import numpy as np

logging.basicConfig(level=logging.INFO)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

BUCKETS = [8, 16, 24, 32]


def synthetic_corpus(n_sent, vocab, rs):
    """Deterministic successor structure: token t -> (3t+1) mod vocab."""
    sents = []
    for _ in range(n_sent):
        length = int(rs.choice([6, 10, 14, 20, 28]))
        t0 = int(rs.randint(vocab))
        s = [t0]
        for _ in range(length - 1):
            s.append((3 * s[-1] + 1) % vocab)
        sents.append(s)
    return sents


def sym_gen_factory(args):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=args.vocab,
                                 output_dim=args.num_embed, name="embed")
        stack = mx.rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                      prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label, name="softmax",
                                    normalization="batch")
        return pred, ("data",), ("softmax_label",)

    return sym_gen


def main(args):
    rs = np.random.RandomState(0)
    train_sents = synthetic_corpus(args.num_sentences, args.vocab, rs)
    val_sents = synthetic_corpus(256, args.vocab, rs)
    train = mx.rnn.BucketSentenceIter(train_sents, args.batch_size,
                                      buckets=BUCKETS)
    val = mx.rnn.BucketSentenceIter(val_sents, args.batch_size,
                                    buckets=BUCKETS)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen_factory(args),
        default_bucket_key=train.default_bucket_key,
        context=mx.tpu())

    metric = mx.metric.Perplexity(ignore_label=None)
    model.fit(train, eval_data=val, eval_metric=metric,
              optimizer=args.optimizer,
              optimizer_params={"learning_rate": args.lr,"wd": 1e-5},
              initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(
                  args.batch_size, 20))
    return model


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--optimizer", type=str, default="adam")
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=32)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-sentences", type=int, default=2048)
    main(p.parse_args())
