#!/usr/bin/env python
"""Sparse linear classification (reference
``example/sparse/linear_classification.py``-style): CSR data batches, a
``row_sparse`` weight, ``sparse.dot`` forward, and ``kvstore.row_sparse_pull``
so only the rows touched by the batch move — the bandwidth win sparse
storage exists for.

    python examples/sparse/linear_classification.py --num-epochs 5
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def synthetic_sparse(n, dim, density, rs):
    """Sparse features whose active indices determine the label."""
    w_true = rs.randn(dim).astype("float32")
    rows = []
    labels = []
    nnz = max(1, int(dim * density))
    for _ in range(n):
        idx = rs.choice(dim, nnz, replace=False)
        vals = rs.rand(nnz).astype("float32")
        x = np.zeros(dim, "float32")
        x[idx] = vals
        rows.append(x)
        labels.append(1.0 if x @ w_true > 0 else 0.0)
    return np.stack(rows), np.asarray(labels, "float32")


def main(args):
    rs = np.random.RandomState(0)
    x_dense, y = synthetic_sparse(args.num_examples, args.dim,
                                  args.density, rs)

    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((args.dim, 1)))
    lr = args.lr
    kv._set_updater(lambda key, grad, weight: weight.__isub__(
        (grad.tostype("default")
         if isinstance(grad, sparse.BaseSparseNDArray) else grad) * lr))

    n_batches = args.num_examples // args.batch_size
    for epoch in range(args.num_epochs):
        correct = 0
        for b in range(n_batches):
            xb = x_dense[b * args.batch_size:(b + 1) * args.batch_size]
            yb = y[b * args.batch_size:(b + 1) * args.batch_size]
            x_csr = sparse.csr_matrix(xb)
            # pull only the rows this batch touches
            touched = np.nonzero(xb.sum(0))[0]
            w_rows = sparse.zeros("row_sparse", (args.dim, 1))
            kv.row_sparse_pull("w", out=w_rows,
                               row_ids=mx.nd.array(touched))
            logits = sparse.dot(x_csr, w_rows.tostype("default"))
            p = 1.0 / (1.0 + np.exp(-logits.asnumpy().ravel()))
            correct += int(((p > 0.5) == (yb > 0.5)).sum())
            # logistic-loss gradient, pushed as row_sparse
            g_dense = xb.T @ (p - yb).reshape(-1, 1) / args.batch_size
            grad = sparse.row_sparse_array(g_dense.astype("float32"))
            kv.push("w", grad)
        print("epoch %d train-acc %.4f"
              % (epoch, correct / (n_batches * args.batch_size)))
    return correct / (n_batches * args.batch_size)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--density", type=float, default=0.02)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--num-examples", type=int, default=2048)
    main(p.parse_args())
