#!/usr/bin/env python
"""Sparse linear classification (reference
``example/sparse/linear_classification.py``-style): CSR data batches, a
``row_sparse`` weight, ``sparse.dot`` forward, and ``kvstore.row_sparse_pull``
so only the rows touched by the batch move — the bandwidth win sparse
storage exists for.

    python examples/sparse/linear_classification.py --num-epochs 5
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def synthetic_sparse(n, dim, density, rs, vary=False):
    """Sparse features whose active indices determine the label.
    ``vary=True`` draws each row's nnz from [nnz/2, 3*nnz/2] — the
    organic per-batch nnz variation real sparse workloads have (and the
    executable cache must absorb; see --nnz-buckets)."""
    w_true = rs.randn(dim).astype("float32")
    rows = []
    labels = []
    base = max(1, int(dim * density))
    for _ in range(n):
        nnz = int(rs.randint(max(1, base // 2), base * 3 // 2 + 1)) \
            if vary else base
        idx = rs.choice(dim, nnz, replace=False)
        vals = rs.rand(nnz).astype("float32")
        x = np.zeros(dim, "float32")
        x[idx] = vals
        rows.append(x)
        labels.append(1.0 if x @ w_true > 0 else 0.0)
    return np.stack(rows), np.asarray(labels, "float32")


def main(args):
    import time

    if args.nnz_buckets:
        os.environ["MXNET_SPARSE_NNZ_BUCKETS"] = "1"
    rs = np.random.RandomState(0)
    x_dense, y = synthetic_sparse(args.num_examples, args.dim,
                                  args.density, rs,
                                  vary=args.vary_nnz)
    shapes_seen = set()   # distinct component shapes = kernel compiles
    t_start = time.perf_counter()

    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((args.dim, 1)))
    lr = args.lr
    kv._set_updater(lambda key, grad, weight: weight.__isub__(
        (grad.tostype("default")
         if isinstance(grad, sparse.BaseSparseNDArray) else grad) * lr))

    n_batches = args.num_examples // args.batch_size
    for epoch in range(args.num_epochs):
        correct = 0
        for b in range(n_batches):
            xb = x_dense[b * args.batch_size:(b + 1) * args.batch_size]
            yb = y[b * args.batch_size:(b + 1) * args.batch_size]
            x_csr = sparse.csr_matrix(xb)
            shapes_seen.add(("csr", x_csr._data.shape[0]))
            # pull only the rows this batch touches
            touched = np.nonzero(xb.sum(0))[0]
            w_rows = sparse.zeros("row_sparse", (args.dim, 1))
            kv.row_sparse_pull("w", out=w_rows,
                               row_ids=mx.nd.array(touched))
            logits = sparse.dot(x_csr, w_rows.tostype("default"))
            p = 1.0 / (1.0 + np.exp(-logits.asnumpy().ravel()))
            correct += int(((p > 0.5) == (yb > 0.5)).sum())
            # logistic-loss gradient, pushed as row_sparse
            g_dense = xb.T @ (p - yb).reshape(-1, 1) / args.batch_size
            grad = sparse.row_sparse_array(g_dense.astype("float32"))
            shapes_seen.add(("rsp", grad._data.shape[0]))
            kv.push("w", grad)
        print("epoch %d train-acc %.4f"
              % (epoch, correct / (n_batches * args.batch_size)))
    dt = time.perf_counter() - t_start
    print("distinct sparse component shapes (≈ kernel compiles): %d | "
          "total %.2fs | buckets=%s vary-nnz=%s"
          % (len(shapes_seen), dt, bool(args.nnz_buckets),
             bool(args.vary_nnz)))
    return correct / (n_batches * args.batch_size)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--density", type=float, default=0.02)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--num-examples", type=int, default=2048)
    p.add_argument("--vary-nnz", action="store_true",
                   help="organic per-row nnz variation")
    p.add_argument("--nnz-buckets", action="store_true",
                   help="MXNET_SPARSE_NNZ_BUCKETS=1: pad nnz to "
                        "power-of-two buckets, bounding compiles at "
                        "O(log max_nnz)")
    main(p.parse_args())
