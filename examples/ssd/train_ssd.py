#!/usr/bin/env python
"""Train a miniature SSD detector end-to-end (reference ``example/ssd``):
``ImageDetIter`` feeds box labels to a multi-scale symbol built from
``MultiBoxPrior``/``MultiBoxTarget``, trained with the reference's
two-part loss (multi-output softmax over classes + smooth-L1 on masked
location offsets), and ``MultiBoxDetection`` decodes + NMSes at
inference.

Hermetic: synthetic images with one colored square per class.

    python examples/ssd/train_ssd.py --num-epochs 10
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)

NUM_CLASSES = 2          # square / circle-ish blob
SIZES = ((0.3, 0.4), (0.6, 0.8))
RATIOS = ((1.0,), (1.0,))


def conv_block(data, num_filter, name, stride=(1, 1)):
    c = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=stride,
                           num_filter=num_filter, no_bias=True, name=name)
    bn = mx.sym.BatchNorm(c, fix_gamma=False, name=name + "_bn")
    return mx.sym.Activation(bn, act_type="relu")


def ssd_symbol(num_classes=NUM_CLASSES):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    body = conv_block(data, 16, "c1", stride=(2, 2))    # 32 -> 16
    body = conv_block(body, 32, "c2", stride=(2, 2))    # -> 8
    fm1 = body                                          # 8x8
    fm2 = conv_block(body, 64, "c3", stride=(2, 2))     # 4x4

    anchors, loc_preds, cls_preds = [], [], []
    for i, fm in enumerate((fm1, fm2)):
        a_per_cell = len(SIZES[i]) + len(RATIOS[i]) - 1
        anchors.append(mx.sym.MultiBoxPrior(
            fm, sizes=SIZES[i], ratios=RATIOS[i], name="anchors%d" % i))
        loc = mx.sym.Convolution(fm, kernel=(3, 3), pad=(1, 1),
                                 num_filter=a_per_cell * 4,
                                 name="loc%d" % i)
        loc = mx.sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_preds.append(mx.sym.Flatten(loc))
        cls = mx.sym.Convolution(fm, kernel=(3, 3), pad=(1, 1),
                                 num_filter=a_per_cell * (num_classes + 1),
                                 name="cls%d" % i)
        cls = mx.sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_preds.append(mx.sym.Reshape(
            cls, shape=(0, -1, num_classes + 1)))

    all_anchors = mx.sym.Concat(*anchors, dim=1, name="all_anchors")
    loc_pred = mx.sym.Concat(*loc_preds, dim=1, name="loc_pred")
    cls_pred = mx.sym.Concat(*cls_preds, dim=1, name="cls_pred_nac")
    # (B, N, C+1) -> (B, C+1, N): the layout MultiBox/softmax expect
    cls_pred = mx.sym.transpose(cls_pred, axes=(0, 2, 1),
                                name="cls_pred")

    loc_t, loc_m, cls_t = mx.sym.MultiBoxTarget(
        all_anchors, label, cls_pred, name="target")
    cls_prob = mx.sym.SoftmaxOutput(cls_pred, cls_t, multi_output=True,
                                    normalization="valid",
                                    name="cls_prob")
    loc_diff = loc_m * (loc_pred - loc_t)
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               grad_scale=1.0, normalization="valid",
                               name="loc_loss")
    # keep targets visible for metrics/decoding without extra binds
    return mx.sym.Group([cls_prob, loc_loss,
                         mx.sym.BlockGrad(cls_t),
                         mx.sym.BlockGrad(loc_pred),
                         mx.sym.BlockGrad(all_anchors)])


def synthetic_batch(rs, n, size=32):
    imgs = np.zeros((n, 3, size, size), "float32")
    labels = np.full((n, 2, 5), -1.0, "float32")
    for i in range(n):
        cls = int(rs.randint(NUM_CLASSES))
        w = rs.randint(size // 4, size // 2)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        imgs[i, cls, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + w) / size]
    return imgs, labels


def main(args):
    rs = np.random.RandomState(0)
    imgs, labels = synthetic_batch(rs, args.num_examples)
    it = mx.io.NDArrayIter(imgs, labels, args.batch_size, shuffle=True,
                           label_name="label")

    sym = ssd_symbol()
    mod = mx.mod.Module(sym, context=mx.tpu(), label_names=("label",),
                        data_names=("data",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        total = 0.0
        for batch in it:
            mod.forward(batch, is_train=True)
            outs = mod.get_outputs()
            cls_prob, _loc_loss, cls_t = outs[0], outs[1], outs[2]
            # cross-entropy of matched anchors (monitoring only)
            p = cls_prob.asnumpy()
            t = cls_t.asnumpy().astype(int)
            valid = t >= 0
            rows = np.take_along_axis(
                p, t[:, None, :].clip(0), axis=1)[:, 0, :]
            total += float(-np.log(rows[valid].clip(1e-9)).mean())
            mod.backward()
            mod.update()
        if first is None:
            first = total
        last = total
        logging.info("epoch %d cls-loss %.4f", epoch, total)

    # inference: decode + NMS
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(imgs[:4])],
                                label=[mx.nd.array(labels[:4])]),
                is_train=False)
    outs = mod.get_outputs()
    cls_prob, loc_pred, anchors = outs[0], outs[3], outs[4]
    det = mx.contrib.nd.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                          nms_threshold=0.5)
    kept = det.asnumpy()[0]
    logging.info("detections (cls, score, box): %s",
                 kept[kept[:, 0] >= 0][:3])
    print("loss first->last: %.3f -> %.3f" % (first, last))
    return first, last


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.005)
    p.add_argument("--num-examples", type=int, default=512)
    main(p.parse_args())
