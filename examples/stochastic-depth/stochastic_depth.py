#!/usr/bin/env python
"""Stochastic depth (reference ``example/stochastic-depth/
sd_cifar10.py`` — Huang et al. 2016): each residual BRANCH is dropped
whole with probability ``death_rate`` during training (a per-sample
Bernoulli gate built from symbolic ``random_uniform``), and scaled by
its survival probability at inference — an ensemble of shallower nets
in one model.

Exercises symbolic random ops beyond Dropout: the gate is a graph-level
``random_uniform -> _greater_scalar -> broadcast_mul`` pattern, train/
inference divergence expressed with two symbols sharing parameters.

    python examples/stochastic-depth/stochastic_depth.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def residual_unit(x, idx, num_filter, death_rate, batch_size,
                  train):
    h = mx.sym.BatchNorm(x, fix_gamma=False, name="u%d_bn1" % idx)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Convolution(h, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), no_bias=True,
                           name="u%d_conv1" % idx)
    h = mx.sym.BatchNorm(h, fix_gamma=False, name="u%d_bn2" % idx)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Convolution(h, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), no_bias=True,
                           name="u%d_conv2" % idx)
    if train:
        # per-sample survival gate: u ~ U(0,1) >= death_rate, scaled by
        # 1/survival so the expectation matches inference
        gate = mx.sym.random_uniform(low=0.0, high=1.0,
                                     shape=(batch_size, 1, 1, 1))
        gate = mx.sym._greater_equal_scalar(gate, scalar=death_rate) \
            if hasattr(mx.sym, "_greater_equal_scalar") else \
            1.0 - mx.sym._lesser_scalar(gate, scalar=death_rate)
        h = mx.sym.broadcast_mul(h, gate) * (1.0 / (1.0 - death_rate))
    return x + h


def get_symbol(units, num_filter, death_rates, batch_size, train):
    x = mx.sym.Variable("data")
    x = mx.sym.Convolution(x, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), no_bias=True, name="conv0")
    for i in range(units):
        x = residual_unit(x, i, num_filter, death_rates[i], batch_size,
                          train)
    x = mx.sym.BatchNorm(x, fix_gamma=False, name="bn_out")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, global_pool=True, kernel=(2, 2),
                       pool_type="avg")
    x = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=4,
                              name="fc")
    return mx.sym.SoftmaxOutput(x, name="softmax")


def synth(n, rs):
    imgs = 0.3 * rs.randn(n, 3, 12, 12).astype("float32")
    labels = rs.randint(0, 4, n).astype("float32")
    yy, xx = np.mgrid[0:12, 0:12]
    for i in range(n):
        q = int(labels[i])
        cy, cx = 3 + 6 * (q // 2), 3 + 6 * (q % 2)
        imgs[i, :, max(0, cy - 2):cy + 2, max(0, cx - 2):cx + 2] += 1.3
    return imgs, labels


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X, y = synth(args.num_examples, rs)
    it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size)
    # linearly increasing death rates over depth (the paper's schedule)
    rates = [args.death_rate * (i + 1) / args.units
             for i in range(args.units)]
    train_sym = get_symbol(args.units, 16, rates, args.batch_size, True)
    mod = mx.mod.Module(train_sym, context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier())

    # inference graph: same parameters, gates replaced by expectation
    arg_params, aux_params = mod.get_params()
    infer_sym = get_symbol(args.units, 16, rates, args.batch_size, False)
    imod = mx.mod.Module(infer_sym, context=mx.tpu(0))
    it.reset()
    imod.bind(data_shapes=it.provide_data,
              label_shapes=it.provide_label, for_training=False)
    imod.set_params(arg_params, aux_params)
    score = dict(imod.score(it, mx.metric.Accuracy()))
    print("stochastic-depth val accuracy %.4f (death_rate %.2f over %d "
          "units)" % (score["accuracy"], args.death_rate, args.units))
    return score["accuracy"]


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--units", type=int, default=4)
    p.add_argument("--death-rate", type=float, default=0.3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=12)
    main(p.parse_args())
