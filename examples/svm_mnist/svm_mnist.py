#!/usr/bin/env python
"""SVM output layer (reference ``example/svm_mnist/``): the same MLP
trained once with ``SVMOutput`` (hinge loss, margin-based) and once
with ``SoftmaxOutput`` — both must learn the task; the SVM variant
demonstrates the margin head end-to-end (L2-regularized squared hinge
by default, ``use_linear=1`` for L1 hinge).

    python examples/svm_mnist/svm_mnist.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(head, num_classes):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=num_classes, name="fc2")
    if head == "svm":
        # L1 hinge (use_linear): bounded per-element gradients —
        # the squared hinge at this feature scale needs a much
        # cooler lr (its gradient grows with the violation)
        return mx.sym.SVMOutput(fc2, name="svm",
                                regularization_coefficient=1.0,
                                use_linear=1)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def synth(n, rs, num_classes=4, dim=32):
    centers = rs.randn(num_classes, dim).astype("float32") * 1.5
    y = rs.randint(0, num_classes, n).astype("float32")
    X = centers[y.astype(int)] + 0.5 * rs.randn(n, dim).astype("float32")
    return X, y


def train(head, X, y, epochs):
    label_name = "svm_label" if head == "svm" else "softmax_label"
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name=label_name)
    mod = mx.mod.Module(get_symbol(head, 4), context=mx.tpu(0),
                        label_names=(label_name,))
    lr = 0.1
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": lr, "momentum": 0.9},
            initializer=mx.init.Xavier())
    mod.forward(mx.io.DataBatch([mx.nd.array(X)], [mx.nd.array(y)]),
                is_train=False)
    scores = mod.get_outputs()[0].asnumpy()
    return float((scores.argmax(1) == y).mean())


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X, y = synth(args.num_examples, rs)
    svm_acc = train("svm", X, y, args.num_epochs)
    sm_acc = train("softmax", X, y, args.num_epochs)
    print("svm acc %.4f | softmax acc %.4f" % (svm_acc, sm_acc))
    return svm_acc, sm_acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=20)
    main(p.parse_args())
