#!/usr/bin/env python
"""Use a PyTorch module inside a symbolic graph (the modern analogue of
the reference Torch plugin, ``plugin/torch`` TorchModule — which bridged
*Lua* Torch; see ``mxnet_tpu/torch.py``).

    python examples/torch/torch_module.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# Custom/torch ops run through jax.pure_callback (host callbacks), which
# PJRT tunnels (axon) do not support -- pin the CPU platform for this
# interop demo (see .claude/skills/verify: env prefix alone is overridden)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
import mxnet_tpu.torch as mxth
import torch


def main():
    # a torch feature extractor inside an mxnet_tpu classifier
    mxth.register_module(
        "torch_features",
        lambda: torch.nn.Sequential(torch.nn.Linear(16, 32),
                                    torch.nn.ReLU()))
    data = mx.sym.Variable("data")
    feats = mx.sym.Custom(data, op_type="torch_features", name="tfeat")
    out = mx.sym.FullyConnected(feats, num_hidden=3, name="head")
    net = mx.sym.SoftmaxOutput(out, name="softmax")

    rs = np.random.RandomState(0)
    x = rs.rand(256, 16).astype("float32")
    w = rs.rand(16, 3).astype("float32")
    y = (x @ w).argmax(1).astype("float32")

    it = mx.io.NDArrayIter(x, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())  # host callbacks -> cpu
    mod.fit(it, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    score = dict(mod.score(mx.io.NDArrayIter(x, y, batch_size=64),
                           mx.metric.create("acc")))
    print("accuracy with torch feature layer:", score)

    # imperative one-liner
    lin = torch.nn.Linear(4, 2)
    print("apply:", mxth.apply(lin, mx.nd.ones((1, 4))).asnumpy())


if __name__ == "__main__":
    main()
