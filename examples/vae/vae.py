#!/usr/bin/env python
"""Variational autoencoder (reference ``example/mxnet_adversarial_vae``
core, minus the GAN half): encoder -> (mu, logvar), reparameterized
sampling INSIDE the symbolic graph (``random_normal`` source op), KL
regularizer attached via ``MakeLoss``, reconstruction head.

The patterns this proves: stochastic nodes in a training graph (the
reparameterization trick), multi-head loss (recon + KL) through
``sym.Group``, and generation by binding the DECODER subgraph alone on
prior samples with the trained weights.

    python examples/vae/vae.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def decoder(z, out_dim, prefix="dec"):
    h = mx.sym.FullyConnected(z, num_hidden=64, name=prefix + "1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=out_dim, name=prefix + "2")
    return mx.sym.Activation(h, act_type="sigmoid", name=prefix + "_out")


def get_symbol(batch, latent, out_dim, kl_weight):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="enc1")
    h = mx.sym.Activation(h, act_type="relu")
    mu = mx.sym.FullyConnected(h, num_hidden=latent, name="enc_mu")
    logvar = mx.sym.FullyConnected(h, num_hidden=latent,
                                   name="enc_logvar")
    eps = mx.sym.random_normal(loc=0.0, scale=1.0,
                               shape=(batch, latent))
    z = mu + mx.sym.exp(0.5 * logvar) * eps      # reparameterization
    recon = decoder(z, out_dim)
    recon_loss = mx.sym.LinearRegressionOutput(recon, name="recon")
    kl = -0.5 * mx.sym.sum(1 + logvar - mu * mu - mx.sym.exp(logvar))
    kl_loss = mx.sym.MakeLoss(kl * (kl_weight / batch), name="kl")
    return mx.sym.Group([recon_loss, kl_loss])


def synth(n, rs):
    """Blob images on a 3-dim manifold, in [0, 1]."""
    yy, xx = np.mgrid[0:16, 0:16]
    imgs = np.empty((n, 256), "float32")
    for i in range(n):
        cy, cx = rs.uniform(4, 12, 2)
        r = rs.uniform(2, 5)
        imgs[i] = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2)
                           / (r * r))).ravel()
    return imgs


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X = synth(args.num_examples, rs)
    it = mx.io.NDArrayIter({"data": X}, {"recon_label": X},
                           batch_size=args.batch_size)
    net = get_symbol(args.batch_size, args.latent, 256, args.kl_weight)
    mod = mx.mod.Module(net, label_names=("recon_label",),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss())

    # reconstruction quality
    mod.forward(mx.io.DataBatch(
        [mx.nd.array(X[:args.batch_size])],
        [mx.nd.array(X[:args.batch_size])]), is_train=False)
    rec = mod.get_outputs()[0].asnumpy()
    mse = float(((rec - X[:args.batch_size]) ** 2).mean())

    # generation: bind the DECODER alone, feed prior samples with the
    # trained weights
    z = mx.sym.Variable("z")
    gen_sym = decoder(z, 256)
    gen = mx.mod.Module(gen_sym, data_names=("z",), label_names=(),
                        context=mx.tpu(0))
    gen.bind(data_shapes=[("z", (args.batch_size, args.latent))],
             for_training=False)
    arg_params, aux_params = mod.get_params()
    gen.set_params({k: v for k, v in arg_params.items()
                    if k.startswith("dec")}, aux_params,
                   allow_missing=True)
    zs = mx.nd.array(rs.randn(args.batch_size,
                              args.latent).astype("float32"))
    gen.forward(mx.io.DataBatch([zs], []), is_train=False)
    samples = gen.get_outputs()[0].asnumpy()
    # prior samples must look blob-like (bright peak, mostly-dark field)
    # and differ from one another (no posterior collapse)
    peak = float(samples.max(axis=1).mean())
    dark = float(np.median(samples))
    diversity = float(samples.std(axis=0).mean())
    print("recon mse %.5f | sample peak %.3f median %.3f "
          "diversity %.4f" % (mse, peak, dark, diversity))
    return mse, peak, dark, diversity


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--num-examples", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--latent", type=int, default=3)
    p.add_argument("--kl-weight", type=float, default=0.05)
    p.add_argument("--num-epochs", type=int, default=30)
    main(p.parse_args())
