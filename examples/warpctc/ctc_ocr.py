#!/usr/bin/env python
"""CTC sequence training (reference ``example/warpctc/``: OCR-style
alignment-free sequence labeling over the warpctc plugin's ``CTCLoss``;
here the native ``ctc_loss`` op — a log-domain ``lax.scan`` forward
recursion, gradient by autodiff).

Toy OCR: each 'image' is a T-step signal carrying K < T digit glyphs at
unknown positions; the model (BiLSTM over the signal) must emit the
digit STRING, alignment unsupervised — exactly what CTC exists for.
Greedy-decode exact-string accuracy must exceed 0.9.

    python examples/warpctc/ctc_ocr.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_symbol(seq_len, num_hidden, vocab):
    """(N, T, F) signal -> BiLSTM -> per-step logits (T, N, C) ->
    CTCLoss via MakeLoss (the warpctc example's net shape)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")        # (N, L) 0-padded, ids 1..9
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="l_"),
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="r_"))
    outputs, _ = cell.unroll(seq_len, inputs=data, merge_outputs=True,
                             layout="NTC")
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="cls")
    pred = mx.sym.Reshape(pred, shape=(-4, -1, seq_len, 0))  # (N,T,C)
    pred = mx.sym.transpose(pred, axes=(1, 0, 2))            # (T,N,C)
    loss = mx.sym.make_loss(mx.sym.mean(
        mx.sym.ctc_loss(pred, label)), name="ctc")
    # expose the softmax for decoding alongside the loss head
    sm = mx.sym.BlockGrad(mx.sym.softmax(pred, axis=-1), name="probs")
    return mx.sym.Group([loss, sm])


def synth(n, seq_len, n_digits, rs):
    """T-step 10-d signal: digit d pulses feature d for 2 steps at a
    random position; label = the digit sequence in order."""
    X = 0.1 * rs.randn(n, seq_len, 10).astype("float32")
    labels = np.zeros((n, n_digits), "float32")
    for i in range(n):
        # distinct, ordered pulse positions with gaps
        pos = np.sort(rs.choice(seq_len // 2 - 1, n_digits,
                                replace=False)) * 2
        digs = rs.randint(0, 9, n_digits)
        for k, (p, d) in enumerate(zip(pos, digs)):
            X[i, p:p + 2, d] += 2.0
            labels[i, k] = d + 1          # CTC ids 1..9 (0 = blank)
    return X, labels


def greedy_decode(probs):
    """(T, N, C) -> list of id sequences (collapse repeats, drop
    blanks)."""
    ids = probs.argmax(-1).T              # (N, T)
    out = []
    for row in ids:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != 0:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def main(args):
    # initializers draw from the process-global rng; seed for reproducible CI
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    X, labels = synth(args.num_examples, args.seq_len, args.n_digits, rs)
    it = mx.io.NDArrayIter({"data": X}, {"label": labels},
                           batch_size=args.batch_size)
    mod = mx.mod.Module(get_symbol(args.seq_len, args.num_hidden, 10),
                        data_names=("data",), label_names=("label",),
                        context=mx.tpu(0))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss())

    mod.forward(mx.io.DataBatch([mx.nd.array(X)],
                                [mx.nd.array(labels)]), is_train=False)
    probs = mod.get_outputs()[1].asnumpy()
    decoded = greedy_decode(probs)
    want = [[int(v) for v in row if v != 0] for row in labels]
    acc = float(np.mean([d == w for d, w in zip(decoded, want)]))
    print("exact-string accuracy %.4f (alignment-free)" % acc)
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--n-digits", type=int, default=3)
    p.add_argument("--num-hidden", type=int, default=48)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-examples", type=int, default=1024)
    p.add_argument("--num-epochs", type=int, default=15)
    main(p.parse_args())
