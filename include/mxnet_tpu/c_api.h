/*
 * C ABI for mxnet_tpu — NDArray / imperative invoke / Symbol / Executor
 * / CachedOp / Autograd / DataIter / KVStore groups, following the
 * reference surface in include/mxnet/c_api.h (NDArray :241-640,
 * imperative invoke c_api_ndarray.cc:548, Symbol :841-1260, Executor
 * :1270-1400, CachedOp c_api_ndarray.cc:611-660, Autograd :680-760,
 * DataIter :1400-1500, KVStore :1513-1770) so C/C++ frontends written
 * against the reference port by relinking.  The deployment-only
 * predictor surface lives in c_predict_api.h.
 *
 * Design: the compute path is XLA via the Python package (the executor
 * compiles bound graphs to single XLA programs); this library embeds
 * CPython and drives the package — the documented layering inversion of
 * this framework (the runtime IS jax/XLA).  Handles own Python object
 * references; every call is GIL-serialized and sets MXGetLastError on
 * failure (return -1).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef const void *AtomicSymbolCreator;

/* dtype codes (reference mshadow convention) */
#define MXNET_TPU_DTYPE_FLOAT32 0
#define MXNET_TPU_DTYPE_FLOAT64 1
#define MXNET_TPU_DTYPE_FLOAT16 2
#define MXNET_TPU_DTYPE_UINT8 3
#define MXNET_TPU_DTYPE_INT32 4
#define MXNET_TPU_DTYPE_INT8 5
#define MXNET_TPU_DTYPE_INT64 6

const char *MXGetLastError();

/* ---- NDArray ---------------------------------------------------------- */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out);
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitAll();

/* ---- op registry + imperative invoke ---------------------------------- */
int MXListAllOpNames(mx_uint *out_size, const char ***out_array);
int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);
/* invoke one op imperatively (reference MXImperativeInvoke,
 * src/c_api/c_api_ndarray.cc:548).  Two modes, matching the reference:
 * with *outputs == NULL on entry, *outputs receives a pointer array
 * (valid until the next invoke on the same thread) whose NDArrayHandle
 * elements are OWNED BY THE CALLER — free each with MXNDArrayFree.
 * With *outputs non-NULL and *num_outputs > 0, results are copied into
 * the caller-provided arrays in place (caller retains ownership).
 * Param values are parsed as Python literals (ints/floats/tuples/
 * bools), falling back to strings. */
int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);

/* ---- Symbol ----------------------------------------------------------- */
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
/* atomic symbol = op + attrs, inputs bound later via Compose
 * (reference MXSymbolCreateAtomicSymbol + MXSymbolCompose) */
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out);
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json);
int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                const char ***out_array);
/* infer shapes from named input shapes (reference MXSymbolInferShape;
 * the CSR (ind_ptr, shape_data) encoding is the reference's).  Output
 * arrays are handle-owned, valid until the next call on the handle. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete);
int MXSymbolFree(SymbolHandle sym);

/* ---- Executor --------------------------------------------------------- */
/* reference MXExecutorBind (c_api.h:1270+): grad_req codes
 * 0=null, 1=write, 3=add */
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store,
                   const mx_uint *grad_req_type, mx_uint num_aux,
                   NDArrayHandle *aux_states, ExecutorHandle *out);
int MXExecutorForward(ExecutorHandle handle, int is_train);
int MXExecutorBackward(ExecutorHandle handle, mx_uint num_head_grads,
                       NDArrayHandle *head_grads);
/* library-owned handle array, valid until the next call on the handle */
int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out);
int MXExecutorFree(ExecutorHandle handle);

/* ---- CachedOp (reference c_api_ndarray.cc:611-660) -------------------- */
typedef void *CachedOpHandle;
int MXCreateCachedOp(SymbolHandle handle, CachedOpHandle *out);
int MXFreeCachedOp(CachedOpHandle handle);
/* inputs follow list_arguments() then list_auxiliary_states() order;
 * output handles follow the MXImperativeInvoke ownership contract
 * (*outputs NULL on entry -> caller owns the returned handles) */
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle *inputs, int *num_outputs,
                     NDArrayHandle **outputs);

/* ---- Autograd (reference c_api.h:680-760) ----------------------------- */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);
int MXAutogradIsRecording(unsigned char *curr);
int MXAutogradIsTraining(unsigned char *curr);
/* grad req codes: 0=null, 1=write, 3=add (reference OpReqType) */
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            mx_uint *reqs_array,
                            NDArrayHandle *grad_handles);
int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle *output_handles);
int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph);
int MXAutogradBackwardEx(mx_uint num_output,
                         NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles, int retain_graph,
                         int is_train);

/* ---- Data iterators (reference c_api.h:1400-1500) --------------------- */
typedef void *DataIterHandle;
typedef const void *DataIterCreator;
int MXListDataIters(mx_uint *out_size, DataIterCreator **out_array);
int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions);
int MXDataIterCreateIter(DataIterCreator creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
/* data/label handles are iterator-owned: valid until the next
 * Next/BeforeFirst/Free on the same iterator; do NOT MXNDArrayFree */
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetIndex(DataIterHandle handle, unsigned long long **out_index,
                       unsigned long long *out_size);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);

/* ---- KVStore (reference c_api.h:1513-1770) ---------------------------- */
typedef void *KVStoreHandle;
typedef void(MXKVStoreUpdater)(int key, NDArrayHandle recv,
                               NDArrayHandle local, void *handle);
typedef void(MXKVStoreServerController)(int head, const char *body,
                                        void *controller_handle);
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);
/* pull writes INTO the caller-provided arrays */
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);
int MXKVStorePullRowSparse(KVStoreHandle handle, mx_uint num,
                           const int *keys, NDArrayHandle *vals,
                           NDArrayHandle *row_ids, int priority);
/* updater runs on every push for 'local' stores; recv/local handles
 * passed to the callback are library-owned (do not free); local must be
 * updated in place (e.g. MXNDArraySyncCopyFromCPU or an invoke with
 * caller-provided outputs) */
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreGetRank(KVStoreHandle handle, int *ret);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *ret);
/* role predicates: this runtime is serverless (XLA collectives +
 * jax.distributed replace the ps-lite server/scheduler roles — SURVEY
 * §2.3 stance), so every process is a worker */
int MXKVStoreIsWorkerNode(int *ret);
int MXKVStoreIsServerNode(int *ret);
int MXKVStoreIsSchedulerNode(int *ret);
int MXKVStoreBarrier(KVStoreHandle handle);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  int barrier_before_exit);
/* serverless: returns immediately with success (no server role exists;
 * kept so reference-contract launch scripts run unmodified) */
int MXKVStoreRunServer(KVStoreHandle handle,
                       MXKVStoreServerController controller,
                       void *controller_handle);
int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char *cmd_body);
int MXKVStoreGetNumDeadNode(KVStoreHandle handle, const int node_id,
                            int *number, const int timeout_sec);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */
