/* C predictor ABI — the deployment surface for C/C++ applications.
 *
 * Mirrors the reference include/mxnet/c_predict_api.h function surface
 * (create from symbol-json + parameter blob, set inputs, forward, read
 * outputs) so applications written against it port by relinking.  The
 * implementation (src/c_predict_api.cc) embeds CPython and drives the
 * XLA-compiled predictor; build it once via:
 *
 *   python -c "from mxnet_tpu import _native; _native._load('c_predict_api')"
 *
 * then link your program against mxnet_tpu/_build/c_predict_api.so with
 * MXNET_TPU_HOME pointing at the framework checkout.
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;

/* Last error message for the calling thread (reference MXGetLastError). */
const char *MXGetLastError();

/* Create a predictor from a symbol JSON string and a parameter blob (the
 * bytes of a prefix-%04d.params file).  input_shape_indptr partitions
 * input_shape_data into one shape tuple per input key.  dev_type/dev_id
 * accepted for parity; XLA owns placement. */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);

/* Copy a row-major float buffer into the named input. */
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

/* Run the compiled forward program. */
int MXPredForward(PredictorHandle handle);

/* Shape of output `index` (valid after MXPredForward; borrowed memory). */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

/* Copy output `index` into a caller buffer of `size` floats. */
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size);

int MXPredFree(PredictorHandle handle);

/* Like MXPredCreate but exposing the named INTERNAL outputs (feature
 * extraction; reference MXPredCreatePartialOut). */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes,
                           const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           mx_uint num_output_nodes,
                           const char **output_keys,
                           PredictorHandle *out);

/* Reference stepping contract.  The bound graph is ONE compiled XLA
 * program (no node boundaries), so the full forward runs at step 0 and
 * *step_left is always 0 afterwards. */
int MXPredPartialForward(PredictorHandle handle, int step,
                         int *step_left);

/* ---- NDList: serialized ndarray collections (mean image files) ------- */
typedef void *NDListHandle;
/* Parse an nd.save container blob; entries are (key, float data, shape).
 * Data/shape/key pointers are list-owned (valid until MXNDListFree). */
int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length);
int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim);
int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif
#endif  /* MXNET_TPU_C_PREDICT_API_H_ */
