// C++ convenience layer over the mxnet_tpu C ABI — the cpp-package
// analogue (reference cpp-package/include/mxnet-cpp/: Symbol, NDArray,
// Operator, Executor wrappers over include/mxnet/c_api.h).  Header-only;
// link against _build/c_api.so.  Ops are surfaced both through the
// generic Operator builder (reference op.h Operator("name").SetParam(...)
// .CreateSymbol()) and through the registry-generated functions in
// op.h (tools/gen_cpp_package.py — the same generated-frontend story as
// the Python nd/sym modules).
#ifndef MXNET_TPU_CPP_MXNET_CPP_H_
#define MXNET_TPU_CPP_MXNET_CPP_H_

#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "../c_api.h"

namespace mxnet_tpu {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

class NDArray {
 public:
  NDArray() = default;
  // own=false wraps a library-owned handle (e.g. MXExecutorOutputs
  // arrays, whose lifetime is the executor's) without freeing it —
  // owning such a handle would double-free.  MXImperativeInvoke output
  // handles are caller-owned (reference contract) and take own=true.
  explicit NDArray(NDArrayHandle h, bool own = true)
      : h_(h, own ? Deleter : NoopDeleter) {}
  NDArray(const std::vector<mx_uint> &shape, int dtype = 0) {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreateEx(shape.data(),
                            static_cast<mx_uint>(shape.size()), 1, 0, 0,
                            dtype, &h));
    h_.reset(h, Deleter);
  }
  NDArray(const std::vector<mx_uint> &shape,
          const std::vector<float> &data)
      : NDArray(shape) {
    SyncCopyFromCPU(data);
  }
  void SyncCopyFromCPU(const std::vector<float> &data) {
    Check(MXNDArraySyncCopyFromCPU(get(), data.data(), data.size()));
  }
  std::vector<float> SyncCopyToCPU() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(get(), out.data(), out.size()));
    return out;
  }
  std::vector<mx_uint> Shape() const {
    mx_uint nd = 0;
    const mx_uint *dims = nullptr;
    Check(MXNDArrayGetShape(get(), &nd, &dims));
    return std::vector<mx_uint>(dims, dims + nd);
  }
  size_t Size() const {
    size_t n = 1;
    for (mx_uint d : Shape()) n *= d;
    return n;
  }
  NDArrayHandle get() const { return h_.get(); }

 private:
  static void Deleter(void *h) {
    if (h) MXNDArrayFree(h);
  }
  static void NoopDeleter(void *) {}
  std::shared_ptr<void> h_;
};

class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle h) : h_(h, Deleter) {}
  static Symbol Variable(const std::string &name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }
  std::string ToJSON() const {
    const char *js = nullptr;
    Check(MXSymbolSaveToJSON(get(), &js));
    return js;
  }
  std::vector<std::string> ListArguments() const {
    return Names("args");
  }
  std::vector<std::string> ListOutputs() const { return Names("outs"); }
  std::vector<std::string> ListAuxiliaryStates() const {
    return Names("aux");
  }
  // named-input shape inference; returns per-argument shapes in
  // ListArguments() order (plus outputs/aux via pointers if wanted)
  std::vector<std::vector<mx_uint>> InferArgShapes(
      const std::map<std::string, std::vector<mx_uint>> &shapes) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0}, data;
    for (auto &kv : shapes) {
      keys.push_back(kv.first.c_str());
      for (mx_uint d : kv.second) data.push_back(d);
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    mx_uint in_n, out_n, aux_n;
    const mx_uint *in_nd, *out_nd, *aux_nd;
    const mx_uint **in_d, **out_d, **aux_d;
    int complete = 0;
    Check(MXSymbolInferShape(
        get(), static_cast<mx_uint>(keys.size()), keys.data(),
        indptr.data(), data.data(), &in_n, &in_nd, &in_d, &out_n,
        &out_nd, &out_d, &aux_n, &aux_nd, &aux_d, &complete));
    std::vector<std::vector<mx_uint>> out;
    for (mx_uint i = 0; i < in_n; ++i)
      out.emplace_back(in_d[i], in_d[i] + in_nd[i]);
    return out;
  }
  SymbolHandle get() const { return h_.get(); }

 private:
  std::vector<std::string> Names(const std::string &which) const {
    mx_uint n = 0;
    const char **arr = nullptr;
    if (which == "args")
      Check(MXSymbolListArguments(get(), &n, &arr));
    else if (which == "outs")
      Check(MXSymbolListOutputs(get(), &n, &arr));
    else
      Check(MXSymbolListAuxiliaryStates(get(), &n, &arr));
    return std::vector<std::string>(arr, arr + n);
  }
  static void Deleter(void *h) {
    if (h) MXSymbolFree(h);
  }
  std::shared_ptr<void> h_;
};

// the reference cpp-package Operator builder: set params, push inputs,
// create the composed symbol (missing parameter inputs are auto-created
// like the Python frontend)
class Operator {
 public:
  explicit Operator(const std::string &op_name) : op_(op_name) {}
  Operator &SetParam(const std::string &key, const std::string &value) {
    params_[key] = value;
    return *this;
  }
  template <typename T>
  Operator &SetParam(const std::string &key, T value) {
    params_[key] = std::to_string(value);
    return *this;
  }
  Operator &SetInput(const std::string &name, const Symbol &sym) {
    input_keys_.push_back(name);
    inputs_.push_back(sym);
    return *this;
  }
  Operator &PushInput(const Symbol &sym) {
    inputs_.push_back(sym);
    return *this;
  }
  Symbol CreateSymbol(const std::string &name = "") {
    if (!input_keys_.empty() && input_keys_.size() != inputs_.size())
      throw std::runtime_error(
          "Operator: SetInput and PushInput cannot be mixed (" +
          std::to_string(input_keys_.size()) + " named vs " +
          std::to_string(inputs_.size()) + " total inputs)");
    std::vector<const char *> keys, vals;
    for (auto &kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    SymbolHandle atomic = nullptr;
    // creators are op-name pointers (MXSymbolGetAtomicSymbolName)
    Check(MXSymbolCreateAtomicSymbol(
        static_cast<AtomicSymbolCreator>(
            static_cast<const void *>(op_.c_str())),
        static_cast<mx_uint>(keys.size()), keys.data(), vals.data(),
        &atomic));
    Symbol result(atomic);
    std::vector<SymbolHandle> args;
    for (auto &s : inputs_) args.push_back(s.get());
    std::vector<const char *> ikeys;
    for (auto &k : input_keys_) ikeys.push_back(k.c_str());
    Check(MXSymbolCompose(
        atomic, name.empty() ? nullptr : name.c_str(),
        static_cast<mx_uint>(args.size()),
        ikeys.size() == args.size() && !ikeys.empty() ? ikeys.data()
                                                      : nullptr,
        args.data()));
    return result;
  }

 private:
  std::string op_;
  std::map<std::string, std::string> params_;
  std::vector<std::string> input_keys_;
  std::vector<Symbol> inputs_;
};

class Executor {
 public:
  // bind with named argument arrays; grad_req 0=null,1=write,3=add
  Executor(const Symbol &sym,
           const std::map<std::string, NDArray> &args,
           const std::map<std::string, NDArray> &arg_grads = {},
           const std::map<std::string, mx_uint> &grad_reqs = {},
           const std::map<std::string, NDArray> &aux = {}) {
    auto arg_names = sym.ListArguments();
    auto aux_names = sym.ListAuxiliaryStates();
    std::vector<NDArrayHandle> in, grads, auxs;
    std::vector<mx_uint> reqs;
    for (auto &n : arg_names) {
      auto it = args.find(n);
      if (it == args.end())
        throw std::runtime_error("missing bind argument: " + n);
      in.push_back(it->second.get());
      auto g = arg_grads.find(n);
      grads.push_back(g == arg_grads.end() ? nullptr : g->second.get());
      auto r = grad_reqs.find(n);
      reqs.push_back(r == grad_reqs.end()
                         ? (g == arg_grads.end() ? 0u : 1u)
                         : r->second);
    }
    for (auto &n : aux_names) {
      auto it = aux.find(n);
      if (it == aux.end())
        throw std::runtime_error("missing aux state: " + n);
      auxs.push_back(it->second.get());
    }
    ExecutorHandle h = nullptr;
    Check(MXExecutorBind(sym.get(), 1, 0,
                         static_cast<mx_uint>(in.size()), in.data(),
                         grads.data(), reqs.data(),
                         static_cast<mx_uint>(auxs.size()), auxs.data(),
                         &h));
    h_.reset(h, Deleter);
  }
  void Forward(bool is_train = false) {
    Check(MXExecutorForward(get(), is_train ? 1 : 0));
  }
  void Backward() { Check(MXExecutorBackward(get(), 0, nullptr)); }
  std::vector<NDArray> Outputs() {
    mx_uint n = 0;
    NDArrayHandle *arr = nullptr;
    Check(MXExecutorOutputs(get(), &n, &arr));
    std::vector<NDArray> out;
    for (mx_uint i = 0; i < n; ++i) {
      // handles stay library-owned; copy through shape+data
      mx_uint nd;
      const mx_uint *dims;
      Check(MXNDArrayGetShape(arr[i], &nd, &dims));
      std::vector<mx_uint> shape(dims, dims + nd);
      size_t total = 1;
      for (mx_uint d : shape) total *= d;
      std::vector<float> host(total);
      Check(MXNDArraySyncCopyToCPU(arr[i], host.data(), host.size()));
      out.emplace_back(shape, host);
    }
    return out;
  }
  ExecutorHandle get() const { return h_.get(); }

 private:
  static void Deleter(void *h) {
    if (h) MXExecutorFree(h);
  }
  std::shared_ptr<void> h_;
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_MXNET_CPP_H_
