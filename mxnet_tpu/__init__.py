"""mxnet_tpu — a TPU-native deep learning framework with the capability
surface of pre-1.0 Apache MXNet (reference: shujonnaha/incubator-mxnet).

See SURVEY.md at the repo root for the reference structural analysis and
README.md for the architecture of this re-design:  imperative NDArray ops
dispatch to cached XLA executables, bound Symbol graphs compile to a single
XLA computation, distribution is jax.sharding meshes + XLA collectives over
ICI/DCN, and Gluon-style blocks hybridize into jitted programs.
"""
import os as _os

if _os.environ.get("JAX_PLATFORMS") == "cpu":
    # honor an explicit CPU request: TPU plugin env exports can override
    # the env var after it is read, so the documented JAX_PLATFORMS=cpu
    # contract silently lands on the accelerator without this pin (the
    # same pin tests/conftest.py applies for pytest).  No-op when the
    # jax backend is already initialized.
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

from . import base
from . import compile_cache
from . import attribute
from .attribute import AttrScope
from .base import MXNetError, TrainingPreempted, RecompileStorm
from . import context
from .context import Context, cpu, gpu, tpu, current_context
from . import random
from . import ops
from . import operator
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import io
from . import recordio
from . import image
from . import kvstore
from . import kvstore as kv
from . import callback
from . import profiler
from . import rtc
from . import visualization
from . import visualization as viz
from . import predictor
from .predictor import Predictor
from . import monitor
from .monitor import Monitor
from . import model
from . import module
from . import module as mod
from .module import Module, BucketingModule
from . import rnn
from . import parallel
from . import test_utils
from .model import save_checkpoint, load_checkpoint
from . import checkpoint
from .checkpoint import CheckpointManager, CheckpointState
from . import testing
from . import models
from . import serve
from . import name
from . import libinfo
from . import executor_manager
from . import kvstore_server
from . import contrib

__version__ = "0.1.0"
