"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime around the compute path is C++ (engine, storage,
IO — SURVEY.md §2.1); on TPU the engine/storage layers are PJRT/XLA, and
the native layer that remains worthwhile is host-side IO.  This module
compiles ``src/*.cc`` with the system ``g++`` on first use (no pybind11
in this image; the ABI is plain C for ctypes) and caches the shared
object under ``mxnet_tpu/_build/``.

Degrades gracefully: if no compiler is available the callers fall back
to their pure-Python paths (``native_recordio() is None``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB = {}

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")


def _python_embed_flags():
    """Compiler/linker flags for embedding CPython (the c_predict_api
    build); via python3-config --embed."""
    import sysconfig

    inc = "-I" + sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return [inc], ["-L" + libdir, "-lpython" + ver]


_EXTRA_FLAGS = {
    # name -> (extra compile flags, extra link flags)
    "c_predict_api": _python_embed_flags,
    "c_api": _python_embed_flags,
    "im2rec": lambda: (["-pthread"], ["-pthread"]),
}


def _load(name):
    """Compile (if stale) and dlopen src/<name>.cc; returns CDLL or
    None."""
    with _LOCK:
        if name in _LIB:
            return _LIB[name]
        src = os.path.join(_SRC_DIR, name + ".cc")
        so = os.path.join(_BUILD_DIR, name + ".so")
        lib = None
        try:
            if os.path.exists(src):
                # stale if older than the source OR any src/*.h it may
                # include (embed_common.h is shared by the ABI libs)
                deps = [src] + [os.path.join(_SRC_DIR, f)
                                for f in os.listdir(_SRC_DIR)
                                if f.endswith(".h")]
                if not os.path.exists(so) or \
                        os.path.getmtime(so) < max(
                            os.path.getmtime(d) for d in deps):
                    os.makedirs(_BUILD_DIR, exist_ok=True)
                    cflags, ldflags = ([], [])
                    if name in _EXTRA_FLAGS:
                        cflags, ldflags = _EXTRA_FLAGS[name]()
                    subprocess.run(
                        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
                        + cflags + ["-o", so, src] + ldflags,
                        check=True, capture_output=True, timeout=120)
                lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError):
            lib = None
        _LIB[name] = lib
        return lib


def native_im2rec():
    """The parallel image->RecordIO packer library, or None."""
    lib = _load("im2rec")
    if lib is None:
        return None
    if not getattr(lib, "_i2r_configured", False):
        lib.i2r_pack.restype = ctypes.c_long
        lib.i2r_pack.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_int]
        lib._i2r_configured = True
    return lib


def pack_recordio(list_path, root, rec_path, idx_path, nthreads=4):
    """Pack already-encoded image files listed in a .lst into .rec/.idx
    with the native parallel packer (the reference's ``tools/im2rec.cc``
    role).  Returns the record count, or None when the native library
    is unavailable; raises on unreadable inputs."""
    from .base import MXNetError

    lib = native_im2rec()
    if lib is None:
        return None
    n = lib.i2r_pack(str(list_path).encode(), str(root or "").encode(),
                     str(rec_path).encode(), str(idx_path).encode(),
                     int(nthreads))
    if n < 0:
        raise MXNetError(
            "native im2rec pack failed (code %d: %s)" % (n, {
                -1: "cannot open list file",
                -2: "unreadable image file",
                -3: "cannot open output",
                -4: "output write failed (disk full?)",
                -5: "image payload exceeds the 2^29-1 byte frame "
                    "limit (length field reserves top 3 bits for "
                    "cflag)"}.get(n, "?")))
    return int(n)


def native_recordio():
    """The recordio scanner library, or None (pure-Python fallback)."""
    lib = _load("recordio")
    if lib is None:
        return None
    if not getattr(lib, "_rio_configured", False):
        lib.rio_scan.restype = ctypes.c_long
        lib.rio_scan.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint32),
                                 ctypes.c_long]
        lib.rio_count.restype = ctypes.c_long
        lib.rio_count.argtypes = [ctypes.c_char_p]
        lib._rio_configured = True
    return lib


def scan_recordio(path):
    """Index a .rec file natively: returns (offsets list, lengths list)
    or None when the native library is unavailable.  Raises on corrupt
    files (negative return codes from the scanner)."""
    from .base import MXNetError

    lib = native_recordio()
    if lib is None:
        return None
    n = lib.rio_count(path.encode())
    if n < 0:
        raise MXNetError("native recordio scan failed on %s (code %d: "
                         "%s)" % (path, n,
                                  {-1: "cannot open", -2: "bad magic",
                                   -3: "truncated",
                                   -4: "bad split framing"}.get(n, "?")))
    offsets = (ctypes.c_uint64 * max(n, 1))()
    lengths = (ctypes.c_uint32 * max(n, 1))()
    n2 = lib.rio_scan(path.encode(), offsets, lengths, n)
    if n2 != n:
        raise MXNetError("native recordio rescan mismatch on %s" % path)
    return list(offsets[:n]), list(lengths[:n])
