"""AttrScope — scoped symbol attributes.

Reference: ``python/mxnet/attribute.py`` (``AttrScope``; the mechanism
behind ``group2ctx`` model parallelism: ``with AttrScope(ctx_group=...)``
tags every symbol built inside the scope).  In the TPU build the
``ctx_group`` attr maps to sharding rather than device placement — the
consumer is ``parallel.sharding`` (rule lists can match on attrs) and
user graph-partitioning logic; lr/wd multipliers (``__lr_mult__`` etc.)
flow through the same channel.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class AttrScope:
    """``with AttrScope(ctx_group='dev1'): ...`` — attributes applied to
    every symbol created in the scope (nested scopes merge, inner
    wins)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings, got "
                                 "%r" % (v,))
        self._attrs = kwargs

    def get(self, attrs=None):
        """Merge scope attrs with explicitly-passed ones (explicit
        wins)."""
        merged = {}
        for scope in _stack():
            merged.update(scope._attrs)
        merged.update(self._attrs)
        if attrs:
            merged.update(attrs)
        return merged

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        _stack().pop()


def current():
    """The merged attribute dict of the active scopes."""
    merged = {}
    for scope in _stack():
        merged.update(scope._attrs)
    return merged
