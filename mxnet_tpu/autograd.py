"""Imperative autograd.

TPU-native replacement for the reference's ``AutogradRuntime`` tape
(``src/ndarray/autograd.{h,cc}``; Python ``python/mxnet/autograd.py``).

The reference records each imperative op as an nnvm node and, on
``backward()``, builds a throwaway ``GraphExecutor`` over the recorded
subgraph (``autograd.cc:229``).  Here the tape records
``(op, attrs, input buffers, output ids, rng key)`` and ``backward()``
replays the tape as a **pure function of the marked variables**, then takes
``jax.vjp`` of that function — gradient construction is delegated to JAX's
program transform instead of per-op FGradient rewrites.  Because recorded
buffers are immutable ``jax.Array``s, later in-place rebinding of an
NDArray cannot corrupt the tape (the reference needs engine version
tracking for the same guarantee).

API surface matches the reference: ``record()``/``pause()``,
``train_mode()``/``predict_mode()``, ``mark_variables``, ``backward``,
``grad``, ``is_recording``/``is_training``.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "mark_variables",
           "backward", "grad", "is_recording", "is_training", "set_recording",
           "set_training"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []          # list of _TapeEntry
        # marked variables, stable across buffer rebinds:
        _state.marked_vars = []   # list of (NDArray, grad NDArray, req)
        # id(jax buffer) -> (NDArray, grad, req); REBUILT at each fresh
        # record() from live buffers — raw ids of freed buffers can be
        # reused by Python, so a persistent id-keyed map would alias
        # rebound variables across training steps.
        _state.marked = {}
    return _state


def _rebuild_marked_map():
    st = _st()
    st.marked = {id(var._data): (var, g, req)
                 for (var, g, req) in st.marked_vars}


class _TapeEntry:
    __slots__ = ("op", "attrs", "in_ids", "in_bufs", "out_ids", "out_bufs",
                 "rng")

    def __init__(self, op, attrs, in_ids, in_bufs, out_ids, out_bufs, rng):
        self.op = op
        self.attrs = attrs
        self.in_ids = in_ids      # buffer ids at record time
        self.in_bufs = in_bufs    # the immutable jax arrays themselves
        self.out_ids = out_ids
        # output buffers are retained too: ids are raw addresses, so a
        # freed output could otherwise alias a later unrelated buffer
        self.out_bufs = out_bufs
        self.rng = rng


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train):
    prev = _st().training
    _st().training = bool(train)
    return prev


def _c_set_recording(is_record):
    """C-ABI entry (MXAutogradSetIsRecording): same fresh-graph
    semantics as entering a ``record()`` scope — an off->on transition
    drops any stale tape and re-keys the marked-variable map."""
    st = _st()
    prev = st.recording
    if is_record and not prev:
        st.tape.clear()
        _rebuild_marked_map()
    st.recording = bool(is_record)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train):
        self._rec, self._train = is_record, train
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec and not st.recording:
            # a fresh outermost record() starts a fresh graph; drops any
            # tape left by a record scope whose backward was never called,
            # and re-keys the marked-variable map to the live buffers
            st.tape.clear()
            _rebuild_marked_map()
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode=True):
    """``with autograd.record():`` — start the tape (+train mode)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (reference ``MXAutogradMarkVariables``)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    st = _st()
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g
        var._grad_req = req
        var._tape_marked = True
        st.marked_vars = [e for e in st.marked_vars if e[0] is not var]
        st.marked_vars.append((var, g, req))
        st.marked[id(var._data)] = (var, g, req)


def _record(op, attrs, in_nds, in_bufs, out_nds, out_bufs, rng_key):
    """Called by imperative_invoke for every op while recording."""
    from .ndarray.ndarray import NDArray

    st = _st()
    # track marked vars through rebinds within this recording: a marked
    # var whose buffer was rebound since the map was built gets re-keyed
    # (buffers recorded on the tape stay alive, so no id reuse here)
    for x in in_nds:
        if isinstance(x, NDArray) and x._tape_marked:
            ident = id(x._data)
            if ident not in st.marked:
                st.marked[ident] = (x, x._grad, x._grad_req)
    n_rng = 1 if op.needs_rng else 0
    st.tape.append(_TapeEntry(
        op, attrs,
        [id(b) for b in in_bufs[n_rng:]],
        list(in_bufs[n_rng:]),
        [id(b) for b in out_bufs],
        list(out_bufs),
        rng_key))


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. all marked variables
    (reference ``MXAutogradBackwardEx`` → ``ComputeGradient``)."""
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray
    from .ops import registry as _reg

    st = _st()
    if not st.tape:
        raise MXNetError("autograd.backward called without recorded graph")

    heads = [h for h in heads]
    head_ids = [id(h._data) for h in heads]

    # leaves = marked variables that actually feed the tape.  A marked
    # NDArray may have been rebound since marking, so resolve each marked
    # buffer id against the tape's recorded input buffers; drop ids that
    # never feed the tape (dedup per variable, keep the live one).
    tape = list(st.tape)
    tape_in = {}
    for entry in tape:
        for bid, buf in zip(entry.in_ids, entry.in_bufs):
            tape_in.setdefault(bid, buf)
    leaf_ids, leaf_entries, leaf_bufs, seen_vars = [], [], [], set()
    for bid, (var, gbuf, req) in st.marked.items():
        if bid not in tape_in:
            continue
        if id(var) in seen_vars:
            continue
        seen_vars.add(id(var))
        leaf_ids.append(bid)
        leaf_entries.append((var, gbuf, req))
        leaf_bufs.append(tape_in[bid])

    def replay(leaf_vals):
        env = dict(zip(leaf_ids, leaf_vals))
        for entry in tape:
            ins = [env.get(bid, buf)
                   for bid, buf in zip(entry.in_ids, entry.in_bufs)]
            if entry.op.needs_rng:
                ins = [entry.rng] + ins
            outs = entry.op.compute(entry.attrs, *ins)
            if not isinstance(outs, tuple):
                outs = (outs,)
            for oid, o in zip(entry.out_ids, outs):
                env[oid] = o
        out_heads = []
        for hid, h in zip(head_ids, heads):
            if hid not in env:
                raise MXNetError("head is not an output of the recorded graph")
            out_heads.append(env[hid])
        return tuple(out_heads)

    out_vals, vjp_fn = jax.vjp(replay, tuple(leaf_bufs))
    if head_grads is None:
        cts = tuple(jnp.ones_like(o) for o in out_vals)
    else:
        cts = tuple(
            jnp.ones_like(o) if hg is None else
            (hg._data if isinstance(hg, NDArray) else jnp.asarray(hg))
            for o, hg in zip(out_vals, head_grads))
    (leaf_grads,) = vjp_fn(cts)

    for (var, gbuf, req), g in zip(leaf_entries, leaf_grads):
        if req == "null" or gbuf is None:
            continue
        if req == "add":
            gbuf._set_data(gbuf._data + g)
        else:
            gbuf._set_data(g)

    if not retain_graph:
        st.tape.clear()


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads wrt variables without touching ``.grad``
    (reference ``autograd.grad``)."""
    from .ndarray.ndarray import zeros, NDArray

    st = _st()
    saved = [(v._grad, v._grad_req, v._tape_marked) for v in variables]
    saved_marked_vars = list(st.marked_vars)
    saved_marked = dict(st.marked)
    gbufs = [zeros(v.shape, v.context, dtype=v.dtype) for v in variables]
    mark_variables(variables, gbufs)
    try:
        backward(heads, head_grads,
                 retain_graph=bool(retain_graph or create_graph),
                 train_mode=train_mode)
    finally:
        for v, (g, r, m) in zip(variables, saved):
            v._grad, v._grad_req, v._tape_marked = g, r, m
        st.marked_vars = saved_marked_vars
        st.marked = saved_marked
    return gbufs
