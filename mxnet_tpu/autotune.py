"""Persistent measured autotuner over the project's knob surface.

The codebase has grown a handful of performance knobs that are still
hand-set per rig: the flash-attention block (``MXNET_ATTN_BLOCK``), the
gradient and ZeRO-3 gather bucket sizes (``MXNET_GRAD_BUCKET_MB``,
``MXNET_ZERO_GATHER_BUCKET_MB``), the serve prefill-bucket ladder, and
now the weight-only quant mode (``MXNET_SERVE_QUANT``).  In the spirit
of TVM's learned schedule search (arXiv 1802.04799) scaled down to a
knob surface XLA already compiles well (arXiv 2301.13062), this module
closes the loop:

* :func:`search` runs a measured greedy coordinate-descent over a knob
  space — the measure callback reports a throughput metric (steps/s or
  tokens/s, from the ``bench_fit.py`` / ``bench_serve.py`` style timing
  loops) plus optional aux metrics (``temp_bytes`` etc. from
  ``memory_analysis`` / the fusion-audit counters) used to break ties
  between knob settings within noise of each other;
* results persist as one JSON record per (kind, model-fingerprint,
  mesh, backend) under ``MXNET_AUTOTUNE_DIR`` (default: an ``autotune``
  directory next to the PR 4 compile cache's home), so the SECOND run
  on the same key is a pure cache hit — stored knobs apply with zero
  measurement passes;
* with ``MXNET_AUTOTUNE`` on, cached knobs auto-apply at build time:
  :func:`apply_serve` folds serve knobs into an env-derived
  ``ServeConfig`` and :func:`apply_train_env` arms the env knobs a
  ``TrainStep`` reads at trace time (never overriding a value the user
  set explicitly);
* every application is recorded in :func:`provenance`, which
  ``compile_cache.report()`` embeds — the compile-report artifact says
  exactly which tuned knobs a process ran under.

``tools/autotune.py`` is the operator CLI: ``--search`` runs measured
searches on this rig, ``--report`` pretty-prints the store.
"""
from __future__ import annotations

import hashlib
import json
import os
import time

from .base import MXNetError, get_env

__all__ = ["autotune_enabled", "store_dir", "budget_s", "fingerprint",
           "fingerprint_symbol", "mesh_desc", "backend_name", "Key",
           "Knob", "AutotuneStore", "search", "apply_serve",
           "apply_train_env", "provenance", "note_applied",
           "clear_applied", "TRAIN_KNOB_ENV"]

DEFAULT_REL_TIE = 0.02

# train-side knobs are applied through the environment because the ops
# read them at trace time (attention.attention_block_size & co.)
TRAIN_KNOB_ENV = {
    "attn_block": "MXNET_ATTN_BLOCK",
    "grad_bucket_mb": "MXNET_GRAD_BUCKET_MB",
    "gather_bucket_mb": "MXNET_ZERO_GATHER_BUCKET_MB",
    # per-layer fp8 allow-list: a tuned comma list of layer names keeps
    # drift-sensitive layers on bf16 while the rest take the fp8 route
    "fp8_layers": "MXNET_FP8_LAYERS",
}

_APPLIED = []  # provenance of knob applications in this process
_ENV_SET = []  # env keys apply_train_env set (so tests can undo)


def autotune_enabled():
    """``MXNET_AUTOTUNE``: apply cached tuned knobs at session /
    TrainStep build (default off — searches themselves are always
    explicit, via tools/autotune.py)."""
    return get_env("MXNET_AUTOTUNE", False, bool)


def store_dir():
    """``MXNET_AUTOTUNE_DIR``: where tuning records persist (default
    ``~/.cache/mxnet_tpu/autotune``, alongside the compile cache)."""
    path = get_env("MXNET_AUTOTUNE_DIR", "", str)
    if not path:
        path = os.path.join(os.path.expanduser("~"), ".cache",
                            "mxnet_tpu", "autotune")
    return path


def budget_s():
    """``MXNET_AUTOTUNE_BUDGET_S``: wall-clock cap for one search's
    measurement passes (0 = unbounded)."""
    return max(0.0, get_env("MXNET_AUTOTUNE_BUDGET_S", 0.0, float))


# -- keys ------------------------------------------------------------------

def fingerprint(params):
    """Stable model fingerprint from parameter names/shapes/dtypes —
    12 hex chars.  Works on arrays, NDArray, ShapeDtypeStructs, and
    quantized ``{"q", "s"}`` records alike."""
    items = []
    for name in sorted(params):
        v = params[name]
        dtype = None
        if isinstance(v, dict) and "q" in v:
            # quantized {"q","s"} record: shape from the codes, dtype
            # the float32 they dequantize to — so a tree quantized
            # after apply_serve still fingerprints like the raw one
            v, dtype = v["q"], "float32"
        v = getattr(v, "_data", v)
        shape = tuple(int(s) for s in getattr(v, "shape", ()))
        if dtype is None:
            dtype = str(getattr(v, "dtype", "?"))
        items.append("%s:%r:%s" % (name, shape, dtype))
    blob = ";".join(items).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def fingerprint_symbol(symbol):
    """Model fingerprint for a symbolic training graph."""
    try:
        blob = symbol.tojson().encode()
    except Exception:  # mxlint: disable=MX008 — repr fallback is the point
        blob = repr(symbol).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def mesh_desc(mesh):
    """Canonical mesh description (``"-"`` for no mesh)."""
    shape = getattr(mesh, "shape", None)
    if not shape:
        return "-"
    return ",".join("%s:%d" % (ax, int(n))
                    for ax, n in sorted(dict(shape).items()))


def backend_name():
    """The jax backend this process measures on (``"cpu"`` when jax is
    not importable — record keys must not require a backend init)."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # mxlint: disable=MX008 — keys must not need a backend
        return "cpu"


class Key(object):
    """Identity of one tuning record: what was tuned (``kind``), for
    which model (``fingerprint``), on which topology (``mesh``,
    ``backend``)."""

    __slots__ = ("kind", "fingerprint", "mesh", "backend")

    def __init__(self, kind, fingerprint, mesh="-", backend=None):
        self.kind = str(kind)
        self.fingerprint = str(fingerprint)
        self.mesh = str(mesh or "-")
        self.backend = str(backend if backend is not None
                           else backend_name())

    @property
    def slug(self):
        mesh = hashlib.sha256(self.mesh.encode()).hexdigest()[:8] \
            if self.mesh != "-" else "none"
        return "%s-%s-%s-%s" % (self.kind, self.fingerprint, mesh,
                                self.backend)

    def __repr__(self):
        return ("Key(kind=%r, fingerprint=%r, mesh=%r, backend=%r)"
                % (self.kind, self.fingerprint, self.mesh, self.backend))


class Knob(object):
    """One searchable dimension: ``values[0]`` is the default the
    coordinate descent starts from."""

    __slots__ = ("name", "values")

    def __init__(self, name, values):
        self.name = str(name)
        self.values = tuple(values)
        if not self.values:
            raise MXNetError("Knob %r has no values" % (name,))


def _space_desc(space):
    # normalize through JSON so equality with a stored record's
    # knob_space is round-trip stable (tuples come back as lists)
    return json.loads(json.dumps({k.name: list(k.values)
                                  for k in space}))


# -- the persistent store --------------------------------------------------

class AutotuneStore(object):
    """One JSON file per record under ``directory`` — the same
    file-per-entry, atomic-replace stance as the compile cache."""

    def __init__(self, directory=None):
        self.directory = directory or store_dir()

    def _path(self, key):
        return os.path.join(self.directory, "autotune-%s.json" % key.slug)

    def get(self, key):
        try:
            with open(self._path(key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def put(self, key, record):
        os.makedirs(self.directory, exist_ok=True)
        path = self._path(key)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    def records(self):
        """Every record in the store (for ``--report``)."""
        out = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for name in names:
            if not (name.startswith("autotune-")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        return out


# -- the search ------------------------------------------------------------

def _measurement(raw):
    if isinstance(raw, dict):
        return {"metric": float(raw["metric"]),
                "aux": dict(raw.get("aux") or {})}
    return {"metric": float(raw), "aux": {}}


def _better(cand, best, rel_tie):
    """Higher metric wins outright; within ``rel_tie`` relative noise,
    lower aux ``temp_bytes`` (the fusion-audit memory signal) breaks
    the tie."""
    m, b = cand["metric"], best["metric"]
    if m > b * (1.0 + rel_tie):
        return True
    if m < b * (1.0 - rel_tie):
        return False
    ca = cand["aux"].get("temp_bytes")
    bb = best["aux"].get("temp_bytes")
    return ca is not None and bb is not None and ca < bb


def search(measure, space, key, store=None, budget=None,
           rel_tie=DEFAULT_REL_TIE, force=False):
    """Greedy coordinate descent over ``space`` (a list of
    :class:`Knob`), measuring each candidate with ``measure(knobs) ->
    metric | {"metric": ..., "aux": {...}}`` (higher is better).

    The record persists under ``key``; a repeat call with the same key
    and knob space returns the stored record WITHOUT calling
    ``measure`` at all (``cache_hit: True``) — the acceptance contract
    for warm builds.  ``budget`` seconds (default
    ``MXNET_AUTOTUNE_BUDGET_S``) bounds measurement time; the baseline
    is always measured, later candidates are skipped once the budget is
    spent (recorded as ``budget_exhausted``).
    """
    space = list(space)
    if not space:
        raise MXNetError("search: empty knob space")
    store = store or AutotuneStore()
    desc = _space_desc(space)
    if not force:
        rec = store.get(key)
        if rec is not None and rec.get("knob_space") == desc:
            rec = dict(rec)
            rec["cache_hit"] = True
            return rec
    if budget is None:
        budget = budget_s()
    t0 = time.perf_counter()
    current = {k.name: k.values[0] for k in space}
    best = _measurement(measure(dict(current)))
    baseline = best["metric"]
    trials = [{"knobs": dict(current), **best}]
    exhausted = False
    for knob in space:
        for val in knob.values[1:]:
            if budget and time.perf_counter() - t0 > budget:
                exhausted = True
                break
            cand = dict(current)
            cand[knob.name] = val
            m = _measurement(measure(dict(cand)))
            trials.append({"knobs": dict(cand), **m})
            if _better(m, best, rel_tie):
                best, current = m, cand
        if exhausted:
            break
    record = {
        "kind": key.kind,
        "fingerprint": key.fingerprint,
        "mesh": key.mesh,
        "backend": key.backend,
        "knob_space": desc,
        "knobs": dict(current),
        "metric": best["metric"],
        "aux": best["aux"],
        "baseline_metric": baseline,
        "speedup_vs_default": (best["metric"] / baseline
                               if baseline else 0.0),
        "measurements": len(trials),
        "trials": trials,
        "elapsed_s": round(time.perf_counter() - t0, 3),
        "budget_exhausted": exhausted,
        "created": time.time(),
    }
    store.put(key, record)
    rec = dict(record)
    rec["cache_hit"] = False
    return rec


# -- application + provenance ----------------------------------------------

def note_applied(record, where, applied):
    """Record one knob application for the compile report."""
    _APPLIED.append({
        "kind": record.get("kind"),
        "fingerprint": record.get("fingerprint"),
        "mesh": record.get("mesh"),
        "backend": record.get("backend"),
        "knobs": dict(record.get("knobs") or {}),
        "applied": list(applied),
        "where": str(where),
        "metric": record.get("metric"),
    })


def provenance():
    """Knob applications this process performed (embedded in
    ``compile_cache.report()`` under ``"autotune"``)."""
    return [dict(rec) for rec in _APPLIED]


def clear_applied():
    """Undo this process's applications: drop the provenance log and
    remove the env vars :func:`apply_train_env` set (test hook)."""
    del _APPLIED[:]
    while _ENV_SET:
        os.environ.pop(_ENV_SET.pop(), None)


def _user_set(env_name):
    """Whether the user set this knob explicitly (either accepted
    prefix counts — see ``base.get_env``)."""
    alt = "MXTPU_" + env_name[len("MXNET_"):]
    return env_name in os.environ or alt in os.environ


def apply_serve(config, params, store=None):
    """Fold a cached serve tuning record into an env-derived
    ``ServeConfig`` (called by ``InferenceSession`` only when the
    caller did NOT pass an explicit config).  Applies ``quant``,
    ``kv_quant`` (int8/fp8 KV-cache pages), ``buckets``,
    ``prefix_pages`` (prefix-cache retention size), ``watermark``
    (preemption free-pool floor; inert until the caller turns
    ``oversub`` on), and the hybrid-stack pair ``layers`` /
    ``window`` (per-layer kind pattern + sliding-window length — a
    tuner that found windowed/SSM layers hold quality can pin the O(1)
    memory stack); anything the record doesn't carry
    keeps the env/default value.  No-op unless ``MXNET_AUTOTUNE`` is on
    and a record exists for this (model-fingerprint, backend)."""
    if not autotune_enabled():
        return config
    import dataclasses

    from .quantize import quant_mode

    store = store or AutotuneStore()
    rec = store.get(Key("serve", fingerprint(params)))
    if not rec:
        return config
    knobs = rec.get("knobs") or {}
    updates = {}
    if "quant" in knobs:
        updates["quant"] = quant_mode(knobs["quant"])
    if "kv_quant" in knobs:
        updates["kv_quant"] = quant_mode(knobs["kv_quant"])
    if "buckets" in knobs:
        updates["buckets"] = tuple(int(b) for b in knobs["buckets"])
    if "prefix_pages" in knobs:
        updates["prefix_pages"] = int(knobs["prefix_pages"])
    if "watermark" in knobs:
        updates["watermark"] = int(knobs["watermark"])
    if "layers" in knobs:
        updates["layers"] = str(knobs["layers"])
    if "window" in knobs:
        updates["window"] = int(knobs["window"])
    if not updates:
        return config
    note_applied(rec, where="InferenceSession",
                 applied=sorted(updates))
    return dataclasses.replace(config, **updates)


def train_key_topology(mesh, plan=None):
    """The Key ``mesh`` field for a train record: the plan fingerprint
    (its own namespace) when a composed plan drives the step — tuned
    knobs for a tp x zero3 plan must not leak onto pure-DP runs of the
    same symbol on the same mesh — else the plain mesh description."""
    if plan is not None:
        return "plan:%s" % plan.fingerprint(mesh)
    return mesh_desc(mesh)


def apply_train_env(symbol, mesh, store=None, plan=None):
    """Arm cached train knobs (:data:`TRAIN_KNOB_ENV`) in the
    environment before a ``TrainStep`` traces — the ops read them at
    trace time.  A knob the user already set (either env prefix) is
    never overridden.  Records are keyed by topology —
    :func:`train_key_topology` — so a composed plan's knobs stay scoped
    to that plan.  Returns the record applied, or None."""
    if not autotune_enabled():
        return None
    store = store or AutotuneStore()
    rec = store.get(Key("train", fingerprint_symbol(symbol),
                        train_key_topology(mesh, plan)))
    if not rec:
        return None
    knobs = rec.get("knobs") or {}
    applied = []
    for kname, env_name in TRAIN_KNOB_ENV.items():
        if kname not in knobs or _user_set(env_name):
            continue
        os.environ[env_name] = str(knobs[kname])
        _ENV_SET.append(env_name)
        applied.append(env_name)
    if applied:
        note_applied(rec, where="TrainStep", applied=applied)
        return rec
    return None
