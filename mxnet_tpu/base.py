"""Base utilities: errors, logging, env config, registries.

TPU-native replacement for the dmlc-core surface the reference uses
(``dmlc::GetEnv`` config, ``dmlc::logging``, ``dmlc::Registry`` — see
reference ``include/mxnet/base.h`` and SURVEY.md §2.1).  There is no C ABI
boundary here: the "registry" that in MXNet lives in C++ and is re-exported
through ``MXSymbolListAtomicSymbolCreators`` is a Python-level registry whose
entries carry JAX/XLA compute functions (see ``mxnet_tpu.ops.registry``).
"""
from __future__ import annotations

import logging
import os

__all__ = ["MXNetError", "TrainingPreempted", "TrainingDiverged",
           "StepHung", "RecompileStorm", "get_env", "string_types",
           "numeric_types", "logger"]

logger = logging.getLogger("mxnet_tpu")


class MXNetError(RuntimeError):
    """Framework error type (mirrors ``MXNetError`` raised through the
    reference's C ABI ``MXGetLastError``, ``python/mxnet/base.py``)."""


class TrainingPreempted(MXNetError):
    """Raised by ``Module.fit`` after a SIGTERM/SIGINT arrived mid-run
    and the final checkpoint was written: the loop stops at the next
    batch boundary instead of dying inside a device call.  ``epoch`` and
    ``nbatch`` name the checkpointed position so launchers can log and
    reschedule with ``fit(resume_from=...)``."""

    def __init__(self, msg, epoch=None, nbatch=None, signum=None):
        super().__init__(msg)
        self.epoch = epoch
        self.nbatch = nbatch
        self.signum = signum


class TrainingDiverged(MXNetError):
    """Raised by the run-health sentinel when training is beyond
    automatic recovery: N consecutive rollbacks (or skip-only policy
    exhausted) without the numerics coming back.  ``epoch``/``nbatch``
    name the position, ``reason`` the anomaly that exhausted the policy
    (see ``docs/health_monitoring.md``)."""

    def __init__(self, msg, epoch=None, nbatch=None, reason=None):
        super().__init__(msg)
        self.epoch = epoch
        self.nbatch = nbatch
        self.reason = reason


class StepHung(MXNetError):
    """Raised (asynchronously, by the step watchdog) when a training
    step made no progress for ``MXNET_STEP_TIMEOUT_S`` seconds: a wedged
    device call, deadlocked collective, or stuck input pipeline.  By the
    time this surfaces the watchdog has already dumped all-thread stacks
    and the last health stats to the artifact named in ``dump_path``
    (pretty-print it with ``tools/diagnose.py``)."""

    def __init__(self, msg="", note=None, dump_path=None):
        # msg defaults to "" because the watchdog delivers this class
        # through PyThreadState_SetAsyncExc, which instantiates it with
        # no arguments; Module.fit re-raises it enriched with the
        # stashed details (health.last_hang_details)
        super().__init__(msg)
        self.note = note
        self.dump_path = dump_path


class RecompileStorm(MXNetError):
    """Raised (under ``MXNET_RECOMPILE_ERROR=1``) when one jitted
    callable has been traced for more distinct input signatures than
    ``MXNET_RECOMPILE_WARN`` allows: the classic silent performance
    cliff where an uncommitted array, a python-scalar weak type, or a
    drifting batch tail recompiles the whole program every step.
    ``name`` is the registered owner, ``signatures`` the distinct count,
    ``diff`` the leaf-level difference against the previous trace (see
    ``mxnet_tpu.compile_cache`` and docs/compilation.md)."""

    def __init__(self, msg, name=None, signatures=None, diff=None):
        super().__init__(msg)
        self.name = name
        self.signatures = signatures
        self.diff = diff


string_types = (str,)
numeric_types = (float, int)


def get_env(name, default, typ=None):
    """Typed env-var lookup, equivalent of ``dmlc::GetEnv``.

    The reference's runtime-config catalog is in
    ``docs/how_to/env_var.md`` (SURVEY.md Appendix B); the TPU build keeps
    the same mechanism with an ``MXTPU_`` prefix while also honoring the
    original ``MXNET_`` names.
    """
    for prefix in ("MXTPU_", "MXNET_", ""):
        key = name if name.startswith(("MXTPU_", "MXNET_")) else prefix + name
        if key in os.environ:
            raw = os.environ[key]
            t = typ or type(default)
            if t is bool:
                return raw not in ("0", "false", "False", "")
            return t(raw)
    return default


class _Registry:
    """Generic name → object registry (equivalent of ``dmlc::Registry``)."""

    def __init__(self, kind):
        self._kind = kind
        self._entries = {}

    def register(self, name, obj=None):
        if obj is None:  # decorator form
            def _reg(o):
                self._entries[name] = o
                return o
            return _reg
        self._entries[name] = obj
        return obj

    def get(self, name):
        if name not in self._entries:
            raise MXNetError(
                "%s %r is not registered (known: %s)"
                % (self._kind, name, sorted(self._entries)))
        return self._entries[name]

    def __contains__(self, name):
        return name in self._entries

    def list(self):
        return sorted(self._entries)
