"""Fault-tolerant checkpointing: atomic writes, retention, resume state.

The reference's checkpoint surface (``model.py`` ``save_checkpoint`` /
``do_checkpoint`` callbacks) assumes the process survives the write; at
pod scale workers are preempted mid-write, so this layer guarantees:

* **Atomicity** — every file (symbol json, params, optimizer states,
  metadata) is written to a temp name and published with ``os.replace``;
  a crash at any point leaves either the previous checkpoint or the new
  one, never a torn file (:func:`atomic_replace`).
* **Rank-0 writes + barrier** — under a dist kvstore only rank 0 touches
  the filesystem, and every rank meets at ``kvstore.barrier()`` after the
  write so no peer resumes against a half-published checkpoint.
* **Retention** — ``keep=N`` garbage-collects all but the newest N
  epochs (params + states + metadata; the symbol file is shared and
  kept).
* **Resume metadata** — a ``-NNNN.meta.json`` sidecar records the epoch,
  the mid-epoch batch offset of a preemption checkpoint, and the
  optimizer ``num_update`` so ``Module.fit(resume_from=...)`` reproduces
  the uninterrupted trajectory exactly (see ``docs/fault_tolerance.md``).

File layout under ``prefix`` (reference filename contract preserved):
``prefix-symbol.json``, ``prefix-NNNN.params``, ``prefix-NNNN.states``,
``prefix-NNNN.meta.json``.  The epoch tag ``NNNN`` counts *completed*
epochs; a preemption checkpoint taken mid-epoch E carries tag E with
``nbatch > 0`` in its metadata.
"""
from __future__ import annotations

import json
import os
import re

from .base import MXNetError, logger

__all__ = ["atomic_replace", "CheckpointManager", "CheckpointState",
           "resolve_resume"]


def atomic_replace(path, write_cb):
    """Write ``path`` atomically: ``write_cb(tmp_path)`` produces the
    content under a temp name (returning the actual path it wrote when a
    writer appends its own suffix, e.g. numpy's ``.npz``), then one
    ``os.replace`` publishes it.  On any failure the temp file is
    removed and ``path`` is untouched — a reader can never observe a
    torn write.  Site ``checkpoint_io`` of the fault harness fires
    between write and publish, the worst possible crash point."""
    from .testing import faults

    tmp = "%s.tmp-%d" % (path, os.getpid())
    actual = None
    try:
        actual = write_cb(tmp) or tmp
        faults.inject("checkpoint_io")
        os.replace(actual, path)
    except BaseException:
        for leftover in {tmp, actual}:
            if leftover and os.path.exists(leftover):
                try:
                    os.remove(leftover)
                except OSError:
                    pass
        raise
    return path


class CheckpointState:
    """Everything ``fit(resume_from=...)`` needs to continue a run."""

    def __init__(self, epoch, nbatch, num_update, symbol, arg_params,
                 aux_params, states_path=None, prefix=None):
        self.epoch = int(epoch)          # completed epochs
        self.nbatch = int(nbatch)        # extra batches into epoch `epoch`
        self.num_update = int(num_update)
        self.symbol = symbol
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.states_path = states_path   # optimizer states file, or None
        self.prefix = prefix

    def __repr__(self):
        return ("CheckpointState(epoch=%d, nbatch=%d, num_update=%d, "
                "states=%r)" % (self.epoch, self.nbatch, self.num_update,
                                self.states_path))


class CheckpointManager:
    """Atomic, rank-aware checkpoint store over a directory.

    ``kvstore`` (optional) supplies rank/barrier semantics: rank 0 writes,
    everyone barriers.  ``keep=N`` retains only the newest N epochs.
    ``save_optimizer_states=False`` drops the states file (params-only
    checkpoints, e.g. for export)."""

    def __init__(self, directory, prefix="model", keep=None, kvstore=None,
                 save_optimizer_states=True):
        if keep is not None and int(keep) < 1:
            raise MXNetError("CheckpointManager keep must be >= 1 or None "
                             "(got %r)" % (keep,))
        self.directory = str(directory)
        self.prefix_name = prefix
        self.keep = None if keep is None else int(keep)
        self.kvstore = kvstore
        self.save_optimizer_states = save_optimizer_states

    @property
    def prefix(self):
        return os.path.join(self.directory, self.prefix_name)

    # -- rank / barrier -------------------------------------------------
    def _rank(self):
        if self.kvstore is not None:
            return int(self.kvstore.rank)
        if os.environ.get("MXNET_COORDINATOR") or \
                os.environ.get("MXNET_NUM_WORKERS"):
            import jax

            return jax.process_index()
        return 0

    def _barrier(self):
        kv = self.kvstore
        if kv is not None and getattr(kv, "_is_dist", False):
            kv.barrier()

    # -- paths ----------------------------------------------------------
    def _params_path(self, epoch):
        return "%s-%04d.params" % (self.prefix, epoch)

    def _states_path(self, epoch):
        return "%s-%04d.states" % (self.prefix, epoch)

    def _meta_path(self, epoch):
        return "%s-%04d.meta.json" % (self.prefix, epoch)

    # -- save -----------------------------------------------------------
    def save(self, module=None, epoch=0, nbatch=0, symbol=None,
             arg_params=None, aux_params=None):
        """Write one checkpoint.  Pass a bound ``module`` (params, aux,
        symbol and optimizer states are pulled from it) or explicit
        ``symbol``/``arg_params``/``aux_params``.  ``epoch`` counts
        completed epochs; ``nbatch > 0`` marks a mid-epoch preemption
        point.  Rank 0 writes, every rank barriers; returns the epoch
        tag."""
        from . import model as model_mod

        epoch = int(epoch)
        if module is not None:
            if symbol is None:
                symbol = module.symbol
            if arg_params is None:
                arg_params, aux_params = module.get_params()
        if arg_params is None:
            raise MXNetError("CheckpointManager.save needs a module or "
                             "explicit arg_params")
        aux_params = aux_params or {}

        if self._rank() == 0:
            os.makedirs(self.directory, exist_ok=True)
            model_mod.save_checkpoint(self.prefix, epoch, symbol,
                                      arg_params, aux_params)
            have_states = False
            if self.save_optimizer_states and module is not None and \
                    getattr(module, "optimizer_initialized", False):
                atomic_replace(self._states_path(epoch),
                               lambda tmp: module.save_optimizer_states(tmp))
                have_states = True
            opt = getattr(module, "_optimizer", None) \
                if module is not None else None
            meta = {"epoch": epoch, "nbatch": int(nbatch),
                    "num_update": int(getattr(opt, "num_update", 0) or 0),
                    "have_states": have_states}
            # meta goes LAST: its presence certifies the whole set; a
            # crash before this line leaves a superseded-but-consistent
            # previous checkpoint as latest()
            atomic_replace(self._meta_path(epoch),
                           lambda tmp: _write_json(tmp, meta))
            self._gc()
        self._barrier()
        return epoch

    # -- discovery / load ----------------------------------------------
    def epochs(self):
        """Sorted epoch tags that have a params file on disk."""
        pat = re.compile(re.escape(self.prefix_name) + r"-(\d{4})\.params$")
        found = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = pat.match(name)
            if m:
                found.append(int(m.group(1)))
        return sorted(found)

    def latest(self):
        """Newest resumable epoch tag, or None when the directory holds
        no checkpoint."""
        eps = self.epochs()
        return eps[-1] if eps else None

    def load(self, epoch=None):
        """Load a checkpoint into a :class:`CheckpointState` (newest when
        ``epoch`` is None)."""
        if epoch is None:
            epoch = self.latest()
            if epoch is None:
                raise MXNetError(
                    "no checkpoint found under %r (prefix %r)"
                    % (self.directory, self.prefix_name))
        from . import model as model_mod

        symbol, arg_params, aux_params = model_mod.load_checkpoint(
            self.prefix, epoch)
        meta = self._read_meta(epoch)
        states = self._states_path(epoch)
        return CheckpointState(
            epoch=meta.get("epoch", epoch), nbatch=meta.get("nbatch", 0),
            num_update=meta.get("num_update", 0), symbol=symbol,
            arg_params=arg_params, aux_params=aux_params,
            states_path=states if os.path.exists(states) else None,
            prefix=self.prefix)

    def _read_meta(self, epoch):
        path = self._meta_path(epoch)
        if not os.path.exists(path):
            # bare save_checkpoint output (no manager metadata): resume
            # from the epoch boundary the filename encodes
            return {"epoch": epoch, "nbatch": 0, "num_update": 0}
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise MXNetError("checkpoint metadata %r is corrupt: %s"
                             % (path, e)) from e

    # -- retention ------------------------------------------------------
    def _gc(self):
        if self.keep is None:
            return
        doomed = self.epochs()[:-self.keep]
        for epoch in doomed:
            for path in (self._params_path(epoch), self._states_path(epoch),
                         self._meta_path(epoch)):
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
                except OSError as e:  # keep training; disk GC can wait
                    logger.warning("checkpoint GC could not remove %s: %s",
                                   path, e)
        if doomed:
            logger.info("checkpoint GC removed epochs %s (keep=%d)",
                        doomed, self.keep)


def _write_json(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)


def resolve_resume(resume_from, kvstore=None):
    """Normalize ``fit(resume_from=...)`` into a :class:`CheckpointState`.

    Accepts a :class:`CheckpointState`, a :class:`CheckpointManager`
    (loads its latest), a ``prefix`` string (directory/prefix of manager
    or bare ``save_checkpoint`` output), or a ``(prefix, epoch)`` pair.
    """
    if isinstance(resume_from, CheckpointState):
        return resume_from
    if isinstance(resume_from, CheckpointManager):
        return resume_from.load()
    if isinstance(resume_from, str):
        head, tail = os.path.split(resume_from)
        return CheckpointManager(head or ".", tail or "model",
                                 kvstore=kvstore).load()
    if isinstance(resume_from, (tuple, list)) and len(resume_from) == 2:
        prefix, epoch = resume_from
        head, tail = os.path.split(str(prefix))
        return CheckpointManager(head or ".", tail or "model",
                                 kvstore=kvstore).load(int(epoch))
    raise MXNetError(
        "resume_from must be a CheckpointState, CheckpointManager, prefix "
        "string or (prefix, epoch) pair (got %r)" % (resume_from,))
