"""Fault-tolerant checkpointing: sharded + checksummed snapshots, async
off-critical-path writes, cross-topology restore.

The reference's checkpoint surface (``model.py`` ``save_checkpoint`` /
``do_checkpoint`` callbacks) assumes the process survives the write; at
pod scale workers are preempted mid-write, slices are reallocated to a
different process count, and disks corrupt shards.  This layer
guarantees:

* **Atomicity** — every file is written to a temp name and published
  with ``os.replace``; a crash at any point leaves either the previous
  checkpoint or the new one, never a torn file (:func:`atomic_replace`).
* **Sharded v2 format** — each host writes only the parameter shards it
  owns (``prefix-NNNN.shard<R>.params`` + a per-rank sidecar recording
  SHA-256/size/piece windows); rank 0 merges the sidecars into a
  ``prefix-NNNN.manifest.json`` holding the GLOBAL shapes/dtypes, the
  serialized ``PartitionSpec`` per parameter, and the step metadata.
  The manifest is written LAST: its presence certifies the whole set.
  ``MXNET_CKPT_FORMAT=1`` restores the legacy single-file layout.
* **Verified loads + quarantine** — ``load()`` re-hashes every shard
  (``MXNET_CKPT_VERIFY``, default on); a truncated or bit-flipped shard
  quarantines the epoch (every file renamed ``*.corrupt``, excluded from
  ``epochs()``/``latest()``/``resolve_resume``) and the load falls back
  to the previous good epoch.  :meth:`CheckpointManager.fsck` (and
  ``tools/ckpt_fsck.py``) audit a directory offline.
* **Topology-elastic restore** — the manifest's global metadata lets
  ``load()`` reassemble full arrays from any shard layout and reshard
  them onto the CURRENT mesh via ``parallel.sharding`` (saved spec
  filtered to the axes that still exist, or explicit
  ``apply_rules``-style rules), so a run saved on N processes resumes
  on M.
* **Async writes** — ``MXNET_CKPT_ASYNC=1`` (or
  ``CheckpointManager(async_writes=True)``): the device→host snapshot
  happens on the calling thread, serialization + fsync happen in a
  bounded ``mxtpu-ckpt-writer`` background thread (depth 1; a second
  ``save()`` first joins the previous write).  Writer errors surface at
  the next ``save()``/``flush()``; the preemption latch in ``Module.fit``
  flushes before raising ``TrainingPreempted``.
* **Rank-0 merge + barrier** — every rank writes its shards and its
  sidecar (even an empty one), meets at a barrier
  (``kvstore.barrier()`` under a dist store, a jax global-device sync
  in the coordinator-env multi-process mode), rank 0 merges the
  sidecars of ranks ``< nproc`` — deleting stale shard files a
  previous save of the same epoch tag under a larger topology left
  behind — and publishes the manifest; a second barrier keeps any
  peer from resuming against a half-published set.  (Async mode
  requires a single-process run and falls back to synchronous writes
  otherwise.)
* **Retention** — ``keep=N`` garbage-collects all but the newest N
  epochs, tolerating concurrently-deleted files, never collecting the
  epoch a resume just loaded, and not counting quarantined epochs.

See ``docs/fault_tolerance.md`` for the on-disk format.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading

from .base import MXNetError, get_env, logger

__all__ = ["atomic_replace", "CheckpointManager", "CheckpointState",
           "CorruptCheckpoint", "resolve_resume"]


class CorruptCheckpoint(MXNetError):
    """A checkpoint epoch failed checksum/coverage verification (it has
    been quarantined on disk as ``*.corrupt``)."""


def atomic_replace(path, write_cb):
    """Write ``path`` atomically: ``write_cb(tmp_path)`` produces the
    content under a temp name (returning the actual path it wrote when a
    writer appends its own suffix, e.g. numpy's ``.npz``), then one
    ``os.replace`` publishes it.  On any failure the temp file is
    removed and ``path`` is untouched — a reader can never observe a
    torn write.  Site ``checkpoint_io`` of the fault harness fires
    between write and publish, the worst possible crash point."""
    from .testing import faults

    tmp = "%s.tmp-%d" % (path, os.getpid())
    actual = None
    try:
        actual = write_cb(tmp) or tmp
        faults.inject("checkpoint_io")
        os.replace(actual, path)
    except BaseException:
        for leftover in {tmp, actual}:
            if leftover and os.path.exists(leftover):
                try:
                    os.remove(leftover)
                except OSError:
                    pass
        raise
    return path


def _np_dtype(name):
    """``np.dtype`` for a manifest dtype string.  ml_dtypes names
    (``bfloat16``, ``float8_e4m3fn``, ...) are only registered with
    numpy once ml_dtypes (or jax) has been imported — resolve them
    explicitly so a process that never touched jax, e.g. an offline
    fsck/CPU tool, can still load such a checkpoint."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, str(name)))
        except (ImportError, AttributeError):
            raise MXNetError(
                "checkpoint dtype %r is not constructible on this host "
                "(ml_dtypes unavailable?)" % (name,)) from None


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _spec_of(data):
    """Serialized PartitionSpec of a jax array's sharding (a list whose
    entries are None, an axis name, or a list of axis names), or None
    when the array carries no named sharding."""
    spec = getattr(getattr(data, "sharding", None), "spec", None)
    if spec is None:
        return None
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(e) for e in entry])
        else:
            out.append(str(entry))
    return out


def _index_windows(index, shape):
    """``jax.Array`` shard index (tuple of slices) -> ``[[start, stop],
    ...]`` per dimension, JSON-serializable."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(int(dim))
        out.append([int(start), int(stop)])
    return out


def assemble_pieces(pieces, params_meta, arrays=None):
    """Merge piece windows into global host arrays — the ONE audited
    window-assembly path, shared by the on-disk restore
    (:meth:`CheckpointManager._assemble`) and the in-memory elastic
    reshard (``parallel/elastic.py``).

    ``pieces`` iterates ``(key, index_windows_or_None, piece)`` triples
    in the :func:`_host_pieces` convention: ``index_windows`` is a
    ``[[start, stop], ...]`` window per dimension, or ``None`` for a
    whole-array piece.  ``params_meta`` maps each key to its global
    ``{"shape", "dtype"}``.  Extension dtypes (bfloat16, fp8) arriving
    as raw same-width bytes — npz stores them as void — are
    reinterpreted via ``.view``, never value-cast, so the round trip is
    bit-identical.  Pass ``arrays`` to accumulate across calls (one per
    shard file); later whole-array pieces replace earlier entries, and
    windowed pieces write into a zeros-initialized destination of the
    global shape."""
    import numpy as np

    arrays = {} if arrays is None else arrays
    for key, idx, piece in pieces:
        meta = params_meta[key]
        want = _np_dtype(meta["dtype"])
        piece = np.asarray(piece)
        if piece.dtype != want and piece.dtype.itemsize == want.itemsize:
            # extension dtypes (bfloat16, fp8) arrive as raw void bytes;
            # reinterpret, don't cast
            piece = piece.view(want)
        if idx is None:
            arrays[key] = piece
            continue
        dst = arrays.get(key)
        if dst is None:
            dst = np.zeros(tuple(meta["shape"]), dtype=want)
            arrays[key] = dst
        dst[tuple(slice(int(a), int(b)) for a, b in idx)] = piece
    return arrays


def _host_pieces(arr, rank):
    """(global_meta, owned_pieces) for one parameter on this rank.

    Fully-addressable arrays (single process, or the replicated CPU rig)
    are owned whole by rank 0; a genuinely multi-host ``jax.Array``
    contributes its addressable shards with ``replica_id == 0``, each
    tagged with its global index window so ANY topology can reassemble
    the full array on load."""
    import numpy as np

    data = getattr(arr, "_data", arr)
    shape = tuple(int(s) for s in getattr(data, "shape", ()))
    meta = {"shape": list(shape),
            "dtype": str(np.dtype(getattr(data, "dtype", "float32"))),
            "spec": _spec_of(data)}
    pieces = []
    if getattr(data, "is_fully_addressable", True):
        if rank == 0:
            pieces.append((None, np.asarray(data)))
    else:
        for s in data.addressable_shards:
            if s.replica_id != 0:
                continue
            pieces.append((_index_windows(s.index, shape),
                           np.asarray(s.data)))
    return meta, pieces


class CheckpointState:
    """Everything ``fit(resume_from=...)`` needs to continue a run."""

    def __init__(self, epoch, nbatch, num_update, symbol, arg_params,
                 aux_params, states_path=None, prefix=None, manifest=None,
                 opt_states=None):
        self.epoch = int(epoch)          # completed epochs
        self.nbatch = int(nbatch)        # extra batches into epoch `epoch`
        self.num_update = int(num_update)
        self.symbol = symbol
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.states_path = states_path   # optimizer states file, or None
        self.prefix = prefix
        self.manifest = manifest         # v2 manifest dict, or None (v1)
        # canonical (weight-shaped, by-name) fused optimizer states from
        # a ZeRO piece-window save, or None — consumed by
        # Module.set_fused_optimizer_states on resume
        self.opt_states = opt_states

    def __repr__(self):
        return ("CheckpointState(epoch=%d, nbatch=%d, num_update=%d, "
                "states=%r)" % (self.epoch, self.nbatch, self.num_update,
                                self.states_path))


class CheckpointManager:
    """Atomic, rank-aware, shard-verified checkpoint store over a
    directory.

    ``kvstore`` (optional) supplies rank/barrier semantics: every rank
    writes its owned shards, rank 0 merges + publishes the manifest,
    everyone barriers.  ``keep=N`` retains only the newest N epochs.
    ``save_optimizer_states=False`` drops the states file (params-only
    checkpoints, e.g. for export).  ``async_writes``/``verify`` override
    ``MXNET_CKPT_ASYNC``/``MXNET_CKPT_VERIFY`` (None = read the env)."""

    def __init__(self, directory, prefix="model", keep=None, kvstore=None,
                 save_optimizer_states=True, async_writes=None, verify=None):
        if keep is not None and int(keep) < 1:
            raise MXNetError("CheckpointManager keep must be >= 1 or None "
                             "(got %r)" % (keep,))
        self.directory = str(directory)
        self.prefix_name = prefix
        self.keep = None if keep is None else int(keep)
        self.kvstore = kvstore
        self.save_optimizer_states = save_optimizer_states
        self.async_writes = bool(get_env("MXNET_CKPT_ASYNC", False, bool)) \
            if async_writes is None else bool(async_writes)
        self.verify = bool(get_env("MXNET_CKPT_VERIFY", True, bool)) \
            if verify is None else bool(verify)
        self._writer = None        # in-flight async write (depth 1)
        self._writer_error = None  # surfaced at the next save()/flush()
        self._warned_async_dist = False
        self._pinned_epoch = None  # epoch a resume loaded; GC-exempt

    @property
    def prefix(self):
        return os.path.join(self.directory, self.prefix_name)

    # -- rank / barrier -------------------------------------------------
    def _rank(self):
        if self.kvstore is not None:
            return int(self.kvstore.rank)
        if os.environ.get("MXNET_COORDINATOR") or \
                os.environ.get("MXNET_NUM_WORKERS"):
            import jax

            return jax.process_index()
        return 0

    def _num_workers(self):
        if self.kvstore is not None:
            return int(getattr(self.kvstore, "num_workers", 1) or 1)
        if os.environ.get("MXNET_COORDINATOR") or \
                os.environ.get("MXNET_NUM_WORKERS"):
            import jax

            return jax.process_count()
        return 1

    def _barrier(self):
        """Rendezvous every writer around the commit.  A dist kvstore
        supplies a bounded barrier; the coordinator-env multi-process
        mode (``MXNET_COORDINATOR``/``MXNET_NUM_WORKERS`` with no
        kvstore) syncs through jax instead — without one, rank 0 could
        publish a manifest missing peer shards, and a later load would
        quarantine files the peers were still writing."""
        kv = self.kvstore
        if kv is not None and getattr(kv, "_is_dist", False):
            kv.barrier()
            return
        if self._num_workers() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("mxtpu-ckpt-commit")

    # -- paths ----------------------------------------------------------
    def _params_path(self, epoch):
        return "%s-%04d.params" % (self.prefix, epoch)

    def _states_path(self, epoch):
        return "%s-%04d.states" % (self.prefix, epoch)

    def _meta_path(self, epoch):
        return "%s-%04d.meta.json" % (self.prefix, epoch)

    def _manifest_path(self, epoch):
        return "%s-%04d.manifest.json" % (self.prefix, epoch)

    def _shard_path(self, epoch, rank):
        return "%s-%04d.shard%d.params" % (self.prefix, epoch, rank)

    def _sidecar_path(self, epoch, rank):
        return "%s-%04d.shard%d.json" % (self.prefix, epoch, rank)

    def _epoch_tag(self, epoch):
        return "%s-%04d." % (self.prefix_name, epoch)

    # -- save -----------------------------------------------------------
    def save(self, module=None, epoch=0, nbatch=0, symbol=None,
             arg_params=None, aux_params=None, zero_states=None,
             zero_params=None, num_update=None, plan=None):
        """Write one checkpoint.  Pass a bound ``module`` (params, aux,
        symbol and optimizer states are pulled from it) or explicit
        ``symbol``/``arg_params``/``aux_params``.  ``epoch`` counts
        completed epochs; ``nbatch > 0`` marks a mid-epoch preemption
        point.  Every rank writes its shards, rank 0 merges + publishes
        the manifest, every rank barriers; returns the epoch tag.

        ``zero_states``: a ``parallel.zero.export_states`` descriptor for
        module-less callers driving ``TrainStep`` directly (a module's
        ZeRO states are exported automatically); the sharded optimizer
        state rides the same piece-window format as the params, so every
        rank contributes its own 1/N windows and ANY topology can
        reassemble them on load.  ``zero_params``: the matching
        ``parallel.zero.export_params`` descriptor for ZeRO-3 runs —
        the at-rest flat parameter tiles ride the same piece windows
        under their ``arg:`` keys (a module's tiles are exported
        automatically), and load reassembles them back to canonical
        shapes, so a ZeRO-3 save restores into ANY topology including
        ``zero=off``.  ``num_update`` overrides the update count
        recorded in the manifest (module-less saves).

        With async writes on, only the device→host snapshot happens on
        this thread; serialization and publish run on the
        ``mxtpu-ckpt-writer`` thread.  A failure of the PREVIOUS async
        write is raised here, before the new snapshot is taken."""
        self._raise_writer_error()

        epoch = int(epoch)
        if module is not None:
            if symbol is None:
                symbol = module.symbol
            if arg_params is None:
                arg_params, aux_params = module.get_params()
        if arg_params is None:
            raise MXNetError("CheckpointManager.save needs a module or "
                             "explicit arg_params")
        aux_params = aux_params or {}

        if int(get_env("MXNET_CKPT_FORMAT", 2, int)) < 2:
            if zero_states is not None or zero_params is not None:
                raise MXNetError(
                    "ZeRO-sharded optimizer state needs the v2 "
                    "piece-window checkpoint format (MXNET_CKPT_FORMAT=2)")
            return self._save_v1(module, epoch, nbatch, symbol,
                                 arg_params, aux_params)

        if plan is None and module is not None:
            # the composed ParallelPlan the module's step trains under:
            # recorded in the manifest so a restore knows what topology
            # wrote the tiles (assembly itself is shape-agnostic — any
            # plan restores onto any other plan or unsharded)
            plan = getattr(getattr(module, "_fused", None), "plan", None)
        os.makedirs(self.directory, exist_ok=True)
        snap = self._snapshot(module, epoch, nbatch, symbol, arg_params,
                              aux_params, zero_states=zero_states,
                              zero_params=zero_params,
                              num_update=num_update, plan=plan)
        if self.async_writes and self._async_eligible():
            self._join_writer()  # depth-1 bound: one write in flight
            t = threading.Thread(target=self._commit_guarded, args=(snap,),
                                 name="mxtpu-ckpt-writer", daemon=True)
            self._writer = t
            t.start()
        else:
            self._commit(snap)
        return epoch

    def _async_eligible(self):
        """Async writes only in a single-process run: the commit path
        barriers (dist kvstore or the coordinator-env jax sync), and a
        barrier from a background thread would race the training step's
        own collectives."""
        kv = self.kvstore
        if (kv is None or not getattr(kv, "_is_dist", False)) and \
                self._num_workers() <= 1:
            return True
        if not self._warned_async_dist:
            self._warned_async_dist = True
            logger.warning(
                "MXNET_CKPT_ASYNC requested in a multi-process run; "
                "falling back to synchronous checkpoint writes (the "
                "commit barrier cannot run off-thread)")
        return False

    def _snapshot(self, module, epoch, nbatch, symbol, arg_params,
                  aux_params, zero_states=None, zero_params=None,
                  num_update=None, plan=None):
        """Device→host snapshot, on the calling thread: after this
        returns, the training loop may mutate params freely."""
        rank = self._rank()
        params_meta, pieces, piece_map = {}, {}, {}

        def _add(key, arr):
            meta, owned = _host_pieces(arr, rank)
            params_meta[key] = meta
            for i, (idx, data) in enumerate(owned):
                pkey = "%s/%d" % (key, i)
                pieces[pkey] = data
                piece_map[pkey] = {"param": key, "index": idx}

        for tag, params in (("arg", arg_params), ("aux", aux_params)):
            for name, arr in params.items():
                _add("%s:%s" % (tag, name), arr)
        if zero_params is None and module is not None:
            exporter = getattr(module, "_export_zero_params", None)
            if exporter is not None:
                zero_params = exporter()
        zparams_meta = None
        if zero_params:
            # ZeRO-3 at-rest tiles ride the same piece windows under
            # their arg: keys, REPLACING any canonical entry of the same
            # name added above — each rank contributes its own 1/N
            # windows, and the load path trims the flat padding back to
            # the canonical shape (manifest "zero_params" records how)
            zparams_meta = {}
            for name, ent in zero_params.items():
                key = "arg:%s" % name
                for pk in [k for k, info in piece_map.items()
                           if info["param"] == key]:
                    pieces.pop(pk, None)
                    piece_map.pop(pk, None)
                zparams_meta[name] = {
                    "logical": int(ent["logical"]),
                    "canonical_shape": [int(s)
                                        for s in ent["canonical_shape"]],
                }
                if ent.get("tp"):
                    # plan-composed TP entry: the flat tile is
                    # shard-major with per-shard padding — the restore
                    # trim inverts per shard (zero.unflatten_tiles)
                    zparams_meta[name]["tp"] = {
                        k: int(v) for k, v in ent["tp"].items()}
                if ent.get("quant"):
                    # weight-only quantized tiles (quantize.quantize_export):
                    # codes ride the pieces, mode + per-channel scales ride
                    # the manifest (float32 via JSON is bit-exact)
                    zparams_meta[name]["quant"] = {
                        "mode": str(ent["quant"]["mode"]),
                        "scales": [float(s)
                                   for s in ent["quant"]["scales"]],
                    }
                _add(key, ent["leaf"])
        if zero_states is None and self.save_optimizer_states and \
                module is not None:
            exporter = getattr(module, "_export_zero_states", None)
            if exporter is not None:
                zero_states = exporter()
        zero_meta = None
        if zero_states is not None:
            # the sharded optimizer state pieces ride the params format:
            # each flat leaf is a sharded jax.Array whose addressable 1/N
            # windows this rank owns (unsharded leaves go whole via rank
            # 0), so elastic reassembly on load is the same code path
            zero_meta = {}
            for name, ent in zero_states.items():
                zero_meta[name] = {
                    "structure": ent["structure"],
                    "num_leaves": len(ent["leaves"]),
                    "flat": [bool(f) for f in ent["flat"]],
                    "logical": int(ent["logical"]),
                    "canonical_shape": [int(s)
                                        for s in ent["canonical_shape"]],
                }
                if ent.get("tp"):
                    zero_meta[name]["tp"] = {
                        k: int(v) for k, v in ent["tp"].items()}
                for j, leaf in enumerate(ent["leaves"]):
                    _add("opt:%s/%d" % (name, j), leaf)
        states = None
        if zero_meta is None and rank == 0 and \
                self.save_optimizer_states and module is not None and \
                getattr(module, "optimizer_initialized", False):
            states = self._states_blob(module)
        opt = getattr(module, "_optimizer", None) \
            if module is not None else None
        if num_update is None:
            num_update = int(getattr(opt, "num_update", 0) or 0)
        plan_meta = None
        if plan is not None:
            plan_meta = (plan.describe() if hasattr(plan, "describe")
                         else dict(plan))
        return {"epoch": epoch, "nbatch": int(nbatch),
                "num_update": int(num_update),
                "symbol_json": symbol.tojson() if symbol is not None
                else None,
                "rank": rank, "nproc": self._num_workers(),
                "params_meta": params_meta, "pieces": pieces,
                "piece_map": piece_map, "states": states,
                "zero_meta": zero_meta, "zparams_meta": zparams_meta,
                "plan": plan_meta}

    def _states_blob(self, module):
        """Optimizer states as bytes (the module API writes files, so
        round-trip through a temp name; this is host-side pickling and
        must run on the snapshot thread — it reads live device state)."""
        import tempfile

        fd, tmp = tempfile.mkstemp(prefix="mxtpu-states-",
                                   dir=self.directory)
        os.close(fd)
        try:
            module.save_optimizer_states(tmp)
            with open(tmp, "rb") as f:
                return f.read()
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _commit_guarded(self, snap):
        try:
            self._commit(snap)
        except BaseException as e:  # surfaced at the next save()/flush()
            self._writer_error = e
            logger.error("async checkpoint write for epoch %d failed: %s",
                         snap["epoch"], e)

    def _commit(self, snap):
        """Serialize + publish one snapshot (writer thread under async).

        Order matters: shards first, sidecars second, barrier, then rank
        0 writes symbol/states and the manifest LAST — the manifest's
        presence certifies the set, so a crash anywhere earlier leaves
        the previous epoch as ``latest()``."""
        import numpy as np

        from .testing import faults

        epoch = snap["epoch"]
        sidecar = {"rank": snap["rank"], "file": None, "sha256": None,
                   "bytes": 0, "pieces": {}}
        if snap["pieces"]:
            shard_path = self._shard_path(epoch, snap["rank"])
            digest = {}

            def _write(tmp):
                with open(tmp, "wb") as f:
                    np.savez(f, **snap["pieces"])
                    f.flush()
                    os.fsync(f.fileno())
                digest["sha256"] = _sha256_file(tmp)
                digest["bytes"] = os.path.getsize(tmp)
                # worst crash point for the sharded writer: bytes down,
                # shard not yet published
                faults.inject("shard_write")
                return tmp

            atomic_replace(shard_path, _write)
            # post-publish corruption hook: the harness may bit-flip or
            # truncate the shard the verifier must then catch
            faults.inject("checkpoint_corrupt", path=shard_path)
            sidecar = {"rank": snap["rank"],
                       "file": os.path.basename(shard_path),
                       "sha256": digest["sha256"],
                       "bytes": digest["bytes"],
                       "pieces": snap["piece_map"]}
        # the sidecar is written even when this rank owns no pieces: a
        # re-save of the same epoch tag after an elastic topology change
        # must overwrite the rank's previous sidecar, or rank 0 would
        # merge the stale pieces into the new manifest
        atomic_replace(self._sidecar_path(epoch, snap["rank"]),
                       lambda tmp: _write_json(tmp, sidecar))
        self._barrier()
        if snap["rank"] == 0:
            if snap["symbol_json"] is not None:
                atomic_replace(
                    "%s-symbol.json" % self.prefix,
                    lambda tmp: _write_text(tmp, snap["symbol_json"]))
            states_entry = None
            if snap["states"] is not None:
                spath = self._states_path(epoch)
                atomic_replace(
                    spath, lambda tmp: _write_bytes(tmp, snap["states"]))
                states_entry = {
                    "file": os.path.basename(spath),
                    "sha256": hashlib.sha256(snap["states"]).hexdigest(),
                    "bytes": len(snap["states"])}
            manifest = {
                "format": 2, "epoch": epoch, "nbatch": snap["nbatch"],
                "num_update": snap["num_update"],
                "have_states": states_entry is not None,
                "num_processes": snap["nproc"],
                "params": snap["params_meta"],
                "shards": self._merge_sidecars(epoch, snap["nproc"]),
                "states": states_entry,
                "zero_states": snap.get("zero_meta"),
                "zero_params": snap.get("zparams_meta"),
                "plan": snap.get("plan")}
            atomic_replace(self._manifest_path(epoch),
                           lambda tmp: _write_json(tmp, manifest))
            self._gc()
        self._barrier()

    def _merge_sidecars(self, epoch, nproc):
        """Merge the sidecars of ranks ``< nproc`` for ``epoch``
        (shared-filesystem contract, same as the v1 rank-0-writes
        protocol).  Leftovers from an EARLIER save of the same epoch tag
        under a different topology — higher-rank sidecars/shards from a
        larger pod preempted mid-epoch, or a shard no fresh sidecar
        references — are deleted before the manifest publishes: merging
        them would let stale parameter windows shadow freshly-saved data
        on restore."""
        pat = re.compile(re.escape(self.prefix_name) +
                         r"-%04d\.shard(\d+)\.(json|params)$" % epoch)
        entries = []
        for name in sorted(os.listdir(self.directory)):
            m = pat.match(name)
            if m:
                entries.append((name, int(m.group(1)), m.group(2)))
        sidecars = []
        for name, rank, kind in entries:
            if kind != "json" or rank >= nproc:
                continue
            with open(os.path.join(self.directory, name)) as f:
                sidecars.append(json.load(f))
        sidecars.sort(key=lambda s: int(s.get("rank", 0)))
        merged = [s for s in sidecars if s.get("file")]
        live = set(s["file"] for s in merged)
        live.update(name for name, rank, kind in entries
                    if kind == "json" and rank < nproc)
        stale = [name for name, rank, kind in entries if name not in live]
        for name in stale:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:
                pass
        if stale:
            logger.warning(
                "checkpoint epoch %d: removed %d stale shard file(s) left "
                "by an earlier save of the same tag (current topology: %d "
                "writer(s)): %s", epoch, len(stale), nproc, stale)
        return merged

    # -- legacy v1 writes -----------------------------------------------
    def _save_v1(self, module, epoch, nbatch, symbol, arg_params,
                 aux_params):
        from . import model as model_mod

        if self._rank() == 0:
            os.makedirs(self.directory, exist_ok=True)
            model_mod.save_checkpoint(self.prefix, epoch, symbol,
                                      arg_params, aux_params)
            have_states = False
            if self.save_optimizer_states and module is not None and \
                    getattr(module, "optimizer_initialized", False):
                atomic_replace(self._states_path(epoch),
                               lambda tmp: module.save_optimizer_states(tmp))
                have_states = True
            opt = getattr(module, "_optimizer", None) \
                if module is not None else None
            meta = {"epoch": epoch, "nbatch": int(nbatch),
                    "num_update": int(getattr(opt, "num_update", 0) or 0),
                    "have_states": have_states}
            # meta goes LAST: its presence certifies the whole set; a
            # crash before this line leaves a superseded-but-consistent
            # previous checkpoint as latest()
            atomic_replace(self._meta_path(epoch),
                           lambda tmp: _write_json(tmp, meta))
            self._gc()
        self._barrier()
        return epoch

    # -- async plumbing -------------------------------------------------
    def _join_writer(self):
        t = self._writer
        if t is not None and t is not threading.current_thread():
            timeout = get_env("MXNET_CKPT_JOIN_TIMEOUT_S", 600.0, float)
            t.join(timeout=timeout if timeout and timeout > 0 else None)
            if t.is_alive():
                # keep the ref: a later flush() re-waits instead of
                # orphaning the write and losing its error
                raise MXNetError(
                    "async checkpoint writer %r did not finish within "
                    "%.0fs (MXNET_CKPT_JOIN_TIMEOUT_S) — disk or "
                    "barrier wedge; the write is still in flight, "
                    "flush() again to re-wait" % (t.name, timeout))
        self._writer = None

    def _raise_writer_error(self):
        self._join_writer()
        err = self._writer_error
        if err is not None:
            self._writer_error = None
            raise err

    def flush(self):
        """Join any in-flight async write and raise its error, if any.
        The preemption latch calls this before the process exits."""
        self._raise_writer_error()

    # -- discovery / load ----------------------------------------------
    def epochs(self):
        """Sorted epoch tags that have a certified set on disk — a v2
        manifest or a v1 params file.  Quarantined (``*.corrupt``)
        epochs never appear here."""
        pat = re.compile(re.escape(self.prefix_name) +
                         r"-(\d{4})\.(params|manifest\.json)$")
        found = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = pat.match(name)
            if m:
                found.add(int(m.group(1)))
        return sorted(found)

    def latest(self):
        """Newest resumable epoch tag, or None when the directory holds
        no checkpoint."""
        eps = self.epochs()
        return eps[-1] if eps else None

    def load(self, epoch=None, mesh=None, sharding=None):
        """Load a checkpoint into a :class:`CheckpointState`.

        ``epoch=None`` loads the newest epoch, FALLING BACK past any
        epoch that fails verification (the corrupt epoch is quarantined
        on disk); an explicit ``epoch`` that fails verification is
        quarantined and raises :class:`CorruptCheckpoint`.

        Elastic restore: v2 params are reassembled into global arrays
        and resharded onto ``mesh`` (default: the active
        ``parallel.current_mesh()``) using the saved per-param
        ``PartitionSpec`` filtered to the axes the mesh still has;
        ``sharding`` overrides with a
        :func:`~mxnet_tpu.parallel.sharding.param_sharding_rules` style
        string or rule list applied through ``apply_rules``."""
        self._join_writer()
        if epoch is not None:
            state = self._load_epoch(int(epoch), mesh, sharding)
            self._pinned_epoch = state.epoch
            return state
        failures = []
        for e in reversed(self.epochs()):
            try:
                state = self._load_epoch(e, mesh, sharding)
                if failures:
                    logger.warning(
                        "checkpoint fallback: loaded epoch %d after "
                        "quarantining %s", e,
                        ", ".join("%d (%s)" % f for f in failures))
                self._pinned_epoch = state.epoch
                return state
            except CorruptCheckpoint as err:
                failures.append((e, str(err).splitlines()[0][:120]))
                continue
        if failures:
            raise MXNetError(
                "no loadable checkpoint under %r (prefix %r): every "
                "candidate failed verification and was quarantined: %s"
                % (self.directory, self.prefix_name,
                   "; ".join("epoch %d: %s" % f for f in failures)))
        raise MXNetError("no checkpoint found under %r (prefix %r)"
                         % (self.directory, self.prefix_name))

    def _load_epoch(self, epoch, mesh=None, sharding=None):
        if not os.path.exists(self._manifest_path(epoch)):
            return self._load_v1(epoch)
        manifest = self._read_manifest(epoch)
        if self.verify:
            problems = self._verify_epoch(manifest)
            if problems:
                self._quarantine(epoch, problems)
                raise CorruptCheckpoint(
                    "checkpoint epoch %d under %r failed verification "
                    "(quarantined as *.corrupt): %s"
                    % (epoch, self.prefix, "; ".join(problems)))
        arrays = self._assemble(manifest)
        opt_states = self._reassemble_zero(manifest, arrays)
        # ZeRO-3 saves record params as flat padded tiles; trim them
        # back to canonical shapes BEFORE layout/reshard so the restore
        # topology (any N, or zero=off) sees ordinary full params.  The
        # saved spec described the flat tile layout and no longer
        # applies.
        zparams = manifest.get("zero_params") or {}
        for name, ent in zparams.items():
            key = "arg:%s" % name
            if key in arrays:
                from .parallel.zero import unflatten_tiles

                arrays[key] = unflatten_tiles(
                    arrays[key].reshape(-1), int(ent["logical"]),
                    [int(s) for s in ent["canonical_shape"]],
                    ent.get("tp"))
                if ent.get("quant"):
                    # quantized tile save: expand the codes back to
                    # float32 with the manifest scales, so every restore
                    # topology sees ordinary full-precision params
                    from .quantize import dequantize_with_meta

                    arrays[key] = dequantize_with_meta(
                        arrays[key], ent["quant"])
        arg_params, aux_params = {}, {}
        resolved_mesh, rule_shardings = self._restore_layout(
            mesh, sharding, arrays)
        for key, arr in arrays.items():
            tag, name = key.split(":", 1)
            spec = (manifest["params"].get(key) or {}).get("spec")
            if tag == "arg" and name in zparams:
                spec = None
            nd = self._reshard(key, arr, spec,
                               resolved_mesh, rule_shardings.get(key))
            (arg_params if tag == "arg" else aux_params)[name] = nd
        symbol = None
        symbol_file = "%s-symbol.json" % self.prefix
        if os.path.exists(symbol_file):
            from . import symbol as sym_mod

            symbol = sym_mod.load(symbol_file)
        states = self._states_path(epoch)
        return CheckpointState(
            epoch=manifest.get("epoch", epoch),
            nbatch=manifest.get("nbatch", 0),
            num_update=manifest.get("num_update", 0), symbol=symbol,
            arg_params=arg_params, aux_params=aux_params,
            states_path=states if os.path.exists(states)
            and opt_states is None else None,
            prefix=self.prefix, manifest=manifest, opt_states=opt_states)

    def _reassemble_zero(self, manifest, arrays):
        """Pop ``opt:`` entries out of the assembled arrays and rebuild
        the canonical (unsharded, full-shape) optimizer-state dict a
        ZeRO save recorded in the manifest.  The caller hands this to
        ``Module.set_fused_optimizer_states``; because the leaves are
        full host arrays the restore topology is free to differ from
        the save topology (N-replica shards → M replicas or unsharded)."""
        zmeta = manifest.get("zero_states")
        if not zmeta:
            # drop stray opt: keys so the arg/aux routing never sees them
            for key in [k for k in arrays if k.startswith("opt:")]:
                arrays.pop(key)
            return None
        from .parallel import zero as _zero

        opt_states = {}
        for name, ent in zmeta.items():
            leaves = []
            for j in range(int(ent["num_leaves"])):
                arr = arrays.pop("opt:%s/%d" % (name, j))
                if ent["flat"][j]:
                    arr = _zero.unflatten_tiles(
                        arr.reshape(-1), int(ent["logical"]),
                        [int(s) for s in ent["canonical_shape"]],
                        ent.get("tp"))
                leaves.append(arr)
            opt_states[name] = _zero.state_unflatten(
                ent["structure"], leaves)
        for key in [k for k in arrays if k.startswith("opt:")]:
            arrays.pop(key)
        return opt_states

    def _load_v1(self, epoch):
        from . import model as model_mod

        symbol, arg_params, aux_params = model_mod.load_checkpoint(
            self.prefix, epoch)
        meta = self._read_meta(epoch)
        states = self._states_path(epoch)
        return CheckpointState(
            epoch=meta.get("epoch", epoch), nbatch=meta.get("nbatch", 0),
            num_update=meta.get("num_update", 0), symbol=symbol,
            arg_params=arg_params, aux_params=aux_params,
            states_path=states if os.path.exists(states) else None,
            prefix=self.prefix)

    def _read_manifest(self, epoch):
        path = self._manifest_path(epoch)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            self._quarantine(epoch, ["unreadable manifest: %s" % e])
            raise CorruptCheckpoint(
                "checkpoint manifest %r is corrupt (epoch quarantined): %s"
                % (path, e)) from e

    # -- verification / quarantine --------------------------------------
    def _verify_epoch(self, manifest):
        """Checksum + coverage audit of one v2 epoch.  Returns a list of
        problem strings (empty = healthy)."""
        problems = []
        blobs = list(manifest.get("shards") or [])
        if manifest.get("states"):
            blobs.append(manifest["states"])
        for entry in blobs:
            path = os.path.join(self.directory, entry["file"])
            if not os.path.exists(path):
                problems.append("missing file %s" % entry["file"])
                continue
            size = os.path.getsize(path)
            if size != entry["bytes"]:
                problems.append("%s truncated: %d bytes, manifest says %d"
                                % (entry["file"], size, entry["bytes"]))
                continue
            if _sha256_file(path) != entry["sha256"]:
                problems.append("%s checksum mismatch (bit rot or torn "
                                "write)" % entry["file"])
        # coverage: the pieces across all shards must tile each param
        covered = {}
        for shard in manifest.get("shards") or []:
            for info in (shard.get("pieces") or {}).values():
                key, idx = info["param"], info["index"]
                meta = manifest["params"].get(key)
                if meta is None:
                    problems.append("shard piece for unknown param %r"
                                    % key)
                    continue
                total = 1
                for d in meta["shape"]:
                    total *= int(d)
                if idx is None:
                    n = total
                else:
                    n = 1
                    for start, stop in idx:
                        n *= max(0, int(stop) - int(start))
                covered[key] = covered.get(key, 0) + n
        for key, meta in (manifest.get("params") or {}).items():
            total = 1
            for d in meta["shape"]:
                total *= int(d)
            n = covered.get(key, 0)
            if n < total:
                problems.append(
                    "param %s incomplete: %d of %d elements present"
                    % (key, n, total))
            elif n > total:
                # a valid save tiles each param exactly once; extra
                # elements mean overlapping windows, i.e. stale shards
                # from another topology's save of the same epoch tag
                problems.append(
                    "param %s over-covered: %d elements for %d (stale or "
                    "overlapping shard pieces)" % (key, n, total))
        return problems

    def _quarantine(self, epoch, problems):
        """Rename every file of ``epoch`` to ``*.corrupt`` so discovery
        (and retention GC) never touches it again; the shared symbol
        file stays.  Best-effort: a concurrently-deleted file is fine."""
        tag = self._epoch_tag(epoch)
        moved = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            if not name.startswith(tag) or name.endswith(".corrupt"):
                continue
            path = os.path.join(self.directory, name)
            try:
                os.replace(path, path + ".corrupt")
                moved.append(name)
            except OSError:
                pass
        logger.error(
            "quarantined checkpoint epoch %d under %r (%s): %s",
            epoch, self.prefix, "; ".join(problems), moved)

    def fsck(self, quarantine=False):
        """Offline audit of every epoch under the prefix: manifest
        readability, shard existence/size/SHA-256, piece coverage (v1
        epochs: params file + metadata readability).  Returns a report
        dict; ``quarantine=True`` additionally renames failing epochs to
        ``*.corrupt`` exactly as a failed ``load()`` would."""
        report = {"directory": self.directory, "prefix": self.prefix_name,
                  "ok": True, "epochs": []}
        try:
            names = os.listdir(self.directory)
        except OSError as e:
            report["ok"] = False
            report["error"] = str(e)
            return report
        report["quarantined_files"] = sorted(
            n for n in names
            if n.startswith(self.prefix_name + "-")
            and n.endswith(".corrupt"))
        for epoch in self.epochs():
            if os.path.exists(self._manifest_path(epoch)):
                fmt = 2
                try:
                    with open(self._manifest_path(epoch)) as f:
                        manifest = json.load(f)
                    problems = self._verify_epoch(manifest)
                except (OSError, ValueError) as e:
                    problems = ["unreadable manifest: %s" % e]
            else:
                fmt = 1
                problems = []
                try:
                    self._read_meta(epoch)
                except MXNetError as e:
                    problems.append(str(e))
                try:
                    from . import model as model_mod

                    model_mod.load_checkpoint(self.prefix, epoch)
                except MXNetError as e:
                    problems.append(str(e))
            entry = {"epoch": epoch, "format": fmt,
                     "ok": not problems, "problems": problems}
            if problems:
                report["ok"] = False
                if quarantine:
                    self._quarantine(epoch, problems)
                    entry["quarantined"] = True
            report["epochs"].append(entry)
        return report

    # -- reassembly / elastic restore -----------------------------------
    def _assemble(self, manifest):
        """Global numpy arrays from whatever shard layout the saving
        topology used — window merging itself lives in the shared
        :func:`assemble_pieces` helper."""
        import numpy as np

        try:  # bf16/fp8 shards need the extension dtypes registered
            import ml_dtypes  # noqa: F401
        except ImportError:
            pass
        arrays = {}
        for shard in manifest.get("shards") or []:
            path = os.path.join(self.directory, shard["file"])
            try:
                npz = np.load(path, allow_pickle=False)
            except Exception as e:
                # verification off (MXNET_CKPT_VERIFY=0) can reach an
                # unreadable shard; surface it as the typed error
                raise CorruptCheckpoint(
                    "checkpoint shard %s is unreadable: %s"
                    % (shard["file"], e)) from e
            with npz as f:
                assemble_pieces(
                    ((info["param"], info["index"], f[pkey])
                     for pkey, info in (shard.get("pieces") or {}).items()),
                    manifest["params"], arrays)
        return arrays

    def _restore_layout(self, mesh, sharding, arrays):
        """(mesh, {key: NamedSharding}) for the elastic restore: the
        CURRENT mesh (argument or ambient scope) plus explicit rule-based
        shardings when the caller passed a style/rule list."""
        if mesh is None:
            from .parallel.mesh import current_mesh

            mesh = current_mesh()
        rule_shardings = {}
        if mesh is not None and sharding is not None:
            from .parallel.sharding import (apply_rules,
                                            param_sharding_rules)

            rules = param_sharding_rules(sharding) \
                if isinstance(sharding, str) else sharding
            rule_shardings = apply_rules(mesh, arrays, rules)
        return mesh, rule_shardings

    def _reshard(self, key, arr, spec, mesh, rule_sharding=None):
        """One param onto the current topology: device_put under the
        saved spec (axes filtered to the mesh that exists NOW) or the
        caller's rule sharding; no mesh -> a host NDArray, and the
        module's own bind/init_optimizer lays it out later."""
        from .ndarray import NDArray, array as nd_array

        if mesh is None:
            return nd_array(arr)
        try:
            import jax

            from .parallel.sharding import sharding_from_spec

            ns = rule_sharding if rule_sharding is not None else \
                sharding_from_spec(mesh, arr.shape, spec)
            return NDArray(jax.device_put(arr, ns))
        except Exception as e:
            logger.warning(
                "elastic reshard of %s onto mesh %s failed (%s); "
                "replicating on host", key,
                dict(getattr(mesh, "shape", {})), e)
            return nd_array(arr)

    def _read_meta(self, epoch):
        manifest_path = self._manifest_path(epoch)
        if os.path.exists(manifest_path):
            try:
                with open(manifest_path) as f:
                    return json.load(f)
            except (OSError, ValueError) as e:
                raise MXNetError("checkpoint manifest %r is corrupt: %s"
                                 % (manifest_path, e)) from e
        path = self._meta_path(epoch)
        if not os.path.exists(path):
            # bare save_checkpoint output (no manager metadata): resume
            # from the epoch boundary the filename encodes
            return {"epoch": epoch, "nbatch": 0, "num_update": 0}
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise MXNetError("checkpoint metadata %r is corrupt: %s"
                             % (path, e)) from e

    # -- retention ------------------------------------------------------
    def _gc(self):
        if self.keep is None:
            return
        # epochs() already excludes quarantined (*.corrupt) epochs, so
        # they neither count toward keep=N nor get collected here; the
        # epoch a resume just loaded is pinned even when it has aged out
        pinned = self._pinned_epoch
        doomed = [e for e in self.epochs()[:-self.keep] if e != pinned]
        if not doomed:
            return
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        removed = []
        for epoch in doomed:
            tag = self._epoch_tag(epoch)
            for name in names:
                if not name.startswith(tag) or name.endswith(".corrupt"):
                    continue
                try:
                    os.remove(os.path.join(self.directory, name))
                except FileNotFoundError:
                    pass  # a concurrent GC/quarantine got there first
                except OSError as e:  # keep training; disk GC can wait
                    logger.warning("checkpoint GC could not remove %s: %s",
                                   name, e)
            removed.append(epoch)
        if removed:
            logger.info("checkpoint GC removed epochs %s (keep=%d)",
                        removed, self.keep)


def _write_json(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())


def _write_text(path, text):
    with open(path, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())


def _write_bytes(path, blob):
    with open(path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())


def resolve_resume(resume_from, kvstore=None):
    """Normalize ``fit(resume_from=...)`` into a :class:`CheckpointState`.

    Accepts a :class:`CheckpointState`, a :class:`CheckpointManager`
    (loads its latest — falling back past quarantined epochs), a
    ``prefix`` string (directory/prefix of manager or bare
    ``save_checkpoint`` output), or a ``(prefix, epoch)`` pair.
    """
    if isinstance(resume_from, CheckpointState):
        return resume_from
    if isinstance(resume_from, CheckpointManager):
        return resume_from.load()
    if isinstance(resume_from, str):
        head, tail = os.path.split(resume_from)
        return CheckpointManager(head or ".", tail or "model",
                                 kvstore=kvstore).load()
    if isinstance(resume_from, (tuple, list)) and len(resume_from) == 2:
        prefix, epoch = resume_from
        head, tail = os.path.split(str(prefix))
        return CheckpointManager(head or ".", tail or "model",
                                 kvstore=kvstore).load(int(epoch))
    raise MXNetError(
        "resume_from must be a CheckpointState, CheckpointManager, prefix "
        "string or (prefix, epoch) pair (got %r)" % (resume_from,))
