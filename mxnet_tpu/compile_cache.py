"""Compile-time subsystem: persistent XLA cache, AOT stats, recompile
guardrails.

Three legs (docs/compilation.md):

1. **Persistent compilation cache** — every bench artifact of rounds
   1-5 died inside XLA compilation before the first measured step; this
   wires JAX's persistent compilation cache behind
   ``MXNET_COMPILE_CACHE_DIR`` (default ``~/.cache/mxnet_tpu/xla``,
   empty string opts out) so a second process running the same model
   deserializes the executable instead of re-running XLA.  The cache
   directory is bounded by ``MXNET_COMPILE_CACHE_MAX_BYTES`` with an
   LRU eviction sweep, and :func:`cache_stats` reports hits / misses /
   bytes / evictions for the current process.  Initialization is lazy:
   the first jit owner (``TrainStep``, ``Executor``, ``CachedOp``, a
   ``Context`` device lookup) calls :func:`ensure_initialized`.

2. **AOT compile accounting** — ``TrainStep.compile(shapes)`` /
   ``Module.prepare_compiled()`` lower-and-compile ahead of time and
   record wall time, FLOPs, and executable size through
   ``profiler.compile_event``; the per-callable stats land on
   ``TrainStep.compile_stats``.

3. **Recompile guardrails** — a process-wide :data:`registry` every jit
   owner registers with.  Each owner holds a :class:`RecompileGuard`
   and reports the signature of every dispatch; the guard counts
   distinct traced signatures, logs a structured warning (with the
   differing shape/dtype/weak-type leaves) past ``MXNET_RECOMPILE_WARN``
   retraces, and raises typed :class:`RecompileStorm` under
   ``MXNET_RECOMPILE_ERROR=1`` — turning silent shape-leak recompiles
   into diagnosable failures.  ``tools/compile_report.py`` pretty-prints
   the artifact written by :func:`write_artifact`.

This is the subsystem the reference framework carried as executor
caching (``simple_bind(shared_exec=...)``, the per-bucket executor cache
in BucketingModule): compilation cost is a first-order lever for a
compiled framework, so it gets measured, cached, and guarded instead of
being absorbed silently into "epoch 0".
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import tempfile
import threading
import time

from .base import MXNetError, RecompileStorm, get_env, logger

__all__ = ["ensure_initialized", "cache_stats", "sweep_cache",
           "signature_of", "diff_signatures", "RecompileGuard",
           "RecompileRegistry", "RecompileStorm", "registry",
           "write_artifact", "track_lru"]

DEFAULT_CACHE_DIR = os.path.join("~", ".cache", "mxnet_tpu", "xla")
# cap chosen for a shared dev box: ~40 ResNet-class executables
DEFAULT_MAX_BYTES = 2 << 30

_lock = threading.Lock()
_state = {
    "initialized": False,
    "enabled": False,
    "dir": None,
    "max_bytes": None,
    "hits": 0,
    "requests": 0,
    "evictions": 0,
    "evicted_bytes": 0,
}


# ---------------------------------------------------------------------------
# leg 1: persistent compilation cache
# ---------------------------------------------------------------------------

def _on_monitoring_event(event, **kwargs):
    # registered with jax's internal monitoring bus; only the two cache
    # counters are interesting, everything else passes through untouched
    if event == "/jax/compilation_cache/cache_hits":
        _state["hits"] += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _state["requests"] += 1


def ensure_initialized():
    """Wire the JAX persistent compilation cache (idempotent, lazy).

    Called by every jit owner right before its first trace; the fast
    path is one boolean check.  Honors:

    * ``MXNET_COMPILE_CACHE_DIR`` — cache directory; default
      ``~/.cache/mxnet_tpu/xla``, empty string disables persistence.
    * ``MXNET_COMPILE_CACHE_MAX_BYTES`` — LRU size cap for the sweep.
    * ``MXNET_COMPILE_CACHE_MIN_COMPILE_S`` — only executables whose
      XLA compile took at least this long are persisted (default 0.5;
      set 0 to persist everything, as the round-trip tests do).
    """
    if _state["initialized"]:
        return _state["enabled"]
    with _lock:
        if _state["initialized"]:
            return _state["enabled"]
        cache_dir = get_env("MXNET_COMPILE_CACHE_DIR", DEFAULT_CACHE_DIR,
                            str)
        _state["max_bytes"] = get_env("MXNET_COMPILE_CACHE_MAX_BYTES",
                                      DEFAULT_MAX_BYTES, int)
        if not cache_dir:
            _state["initialized"] = True
            _state["enabled"] = False
            return False
        cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
        try:
            import jax

            from jax._src import monitoring as _monitoring

            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_enable_compilation_cache", True)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                get_env("MXNET_COMPILE_CACHE_MIN_COMPILE_S", 0.5, float))
            # entry size gating would silently drop small-model
            # executables — the LRU sweep is the size policy here
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)
            _monitoring.register_event_listener(_on_monitoring_event)
            _state["dir"] = cache_dir
            _state["enabled"] = True
            # bound the directory NOW (a previous run may have blown the
            # cap) and again at exit (this run's own entries)
            sweep_cache()
            atexit.register(sweep_cache)
        except Exception as e:  # cache is an optimization, never fatal
            logger.warning("persistent compilation cache unavailable "
                           "(%s); compiles will not be reused across "
                           "processes", e)
            _state["enabled"] = False
        _state["initialized"] = True
        return _state["enabled"]


def _cache_entries(cache_dir):
    """[(path, size, last-use timestamp)] for every cache file."""
    entries = []
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return entries
    for name in names:
        path = os.path.join(cache_dir, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        if not os.path.isfile(path):
            continue
        # atime when the mount tracks it (a cache hit touches it),
        # else mtime — both give oldest-first eviction order
        entries.append((path, st.st_size, max(st.st_atime, st.st_mtime)))
    return entries


def sweep_cache(cache_dir=None, max_bytes=None):
    """LRU eviction sweep: delete least-recently-used cache entries
    until the directory fits ``max_bytes``.  Returns (entries, bytes)
    remaining.  Safe to call concurrently with running processes — an
    evicted entry just recompiles on its next use."""
    cache_dir = cache_dir or _state["dir"]
    if max_bytes is None:
        max_bytes = _state["max_bytes"]
        if max_bytes is None:
            max_bytes = get_env("MXNET_COMPILE_CACHE_MAX_BYTES",
                                DEFAULT_MAX_BYTES, int)
    if not cache_dir:
        return 0, 0
    entries = _cache_entries(cache_dir)
    total = sum(size for _, size, _ in entries)
    if total <= max_bytes:
        return len(entries), total
    entries.sort(key=lambda e: e[2])  # oldest last-use first
    removed = 0
    freed = 0
    for path, size, _ in entries:
        if total <= max_bytes:
            break
        try:
            os.remove(path)
        except OSError:
            continue
        total -= size
        removed += 1
        freed += size
        _state["evictions"] += 1
        _state["evicted_bytes"] += size
    if removed:
        logger.info("compile cache sweep: evicted %d entries (%d bytes "
                    "over the %d-byte cap) from %s", removed,
                    freed, max_bytes, cache_dir)
    return len(entries) - removed, total


def cache_stats():
    """Persistent-cache statistics for this process.

    ``hits``/``misses`` count XLA compile requests served from /
    missed by the persistent cache since initialization (misses include
    executables too cheap to persist); ``entries``/``bytes`` are the
    cache directory's current on-disk state; ``evictions`` counts
    entries this process's LRU sweeps removed."""
    entries, nbytes = 0, 0
    if _state["dir"]:
        found = _cache_entries(_state["dir"])
        entries = len(found)
        nbytes = sum(size for _, size, _ in found)
    return {
        "enabled": _state["enabled"],
        "dir": _state["dir"],
        "hits": _state["hits"],
        "misses": max(0, _state["requests"] - _state["hits"]),
        "requests": _state["requests"],
        "entries": entries,
        "bytes": nbytes,
        "max_bytes": _state["max_bytes"],
        "evictions": _state["evictions"],
        "evicted_bytes": _state["evicted_bytes"],
    }


# ---------------------------------------------------------------------------
# leg 3: recompile guardrails
# ---------------------------------------------------------------------------

def _describe_leaf(x):
    """(shape, dtype, weak_type) identity of one jit-signature leaf —
    exactly the triple jax keys its trace cache on.  Python scalars are
    the classic weak-type leak, so they get named as such."""
    if isinstance(x, bool):
        return ("py_bool", "weak")
    if isinstance(x, int):
        return ("py_int", "weak")
    if isinstance(x, float):
        return ("py_float", "weak")
    if isinstance(x, complex):
        return ("py_complex", "weak")
    shape = getattr(x, "shape", None)
    if shape is None:
        return (type(x).__name__,)
    return (tuple(shape), str(getattr(x, "dtype", "?")),
            bool(getattr(x, "weak_type", False)))


def signature_of(*trees):
    """Hashable (path, leaf-identity) signature of a jit call's inputs.

    Two calls with equal signatures hit the same traced program; a new
    signature is a retrace."""
    from jax.tree_util import tree_flatten_with_path, keystr

    sig = []
    for i, tree in enumerate(trees):
        leaves, _ = tree_flatten_with_path(tree)
        for path, leaf in leaves:
            sig.append(("%d%s" % (i, keystr(path)), _describe_leaf(leaf)))
    return tuple(sig)


def diff_signatures(old, new):
    """Leaf-level difference between two signatures: the argument paths
    whose shape/dtype/weak-type changed (or appeared/disappeared)."""
    old_map = dict(old)
    new_map = dict(new)
    lines = []
    for path in sorted(set(old_map) | set(new_map), key=str):
        a, b = old_map.get(path), new_map.get(path)
        if a == b:
            continue
        if a is None:
            lines.append("%s: (absent) -> %r" % (path, b))
        elif b is None:
            lines.append("%s: %r -> (absent)" % (path, a))
        else:
            lines.append("%s: %r -> %r" % (path, a, b))
    return lines


class RecompileGuard:
    """Per-callable retrace counter.

    The owner calls :meth:`observe` with the signature of each dispatch;
    the guard tracks distinct signatures (``signatures``), total traces
    including rebuilds of evicted entries (``traces``), and total calls
    (``calls``).  Past ``MXNET_RECOMPILE_WARN`` distinct signatures it
    logs one structured warning per further retrace — naming the leaves
    that differ from the previous trace — and raises
    :class:`RecompileStorm` when ``MXNET_RECOMPILE_ERROR=1``."""

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.traces = 0
        self._seen = {}          # signature -> first-seen trace index
        self._last_sig = None
        self._lock = threading.Lock()

    @property
    def signatures(self):
        return len(self._seen)

    def observe(self, sig, force=False):
        """Record one dispatch.  ``force=True`` counts a trace even for
        a previously seen signature (a rebuild after cache eviction).
        Returns True when this call traced."""
        with self._lock:
            self.calls += 1
            new = sig not in self._seen
            if new:
                self._seen[sig] = self.traces
            traced = new or force
            if traced:
                self.traces += 1
            prev, self._last_sig = self._last_sig, sig
            n = self.signatures
        if not new or n <= 1:
            return traced
        warn_after = get_env("MXNET_RECOMPILE_WARN", 3, int)
        if n > warn_after:
            diff = diff_signatures(prev, sig) if prev is not None else []
            msg = ("recompile guard: %r has been traced for %d distinct "
                   "input signatures (threshold %d) — every new "
                   "signature is a full XLA recompile. Changed vs the "
                   "previous trace:\n  %s\nCommon causes: uncommitted "
                   "arrays, python-scalar weak types, drifting batch "
                   "tails (see docs/compilation.md)."
                   % (self.name, n, warn_after,
                      "\n  ".join(diff) or "(no leaf-level difference — "
                      "tree structure changed)"))
            if get_env("MXNET_RECOMPILE_ERROR", False, bool):
                raise RecompileStorm(msg, name=self.name, signatures=n,
                                     diff=diff)
            logger.warning(msg)
        return traced

    def snapshot(self):
        return {"name": self.name, "calls": self.calls,
                "traces": self.traces, "signatures": self.signatures}


class RecompileRegistry:
    """Process-wide registry of :class:`RecompileGuard` s.

    ``guard(name)`` returns the existing guard for ``name`` (so a
    rebuilt owner — an ``Executor`` recreated by ``reshape`` on a
    drifting batch size — keeps accumulating into the same counter,
    which is exactly the storm the guard exists to catch)."""

    def __init__(self):
        self._guards = {}
        self._lock = threading.Lock()

    def guard(self, name):
        with self._lock:
            g = self._guards.get(name)
            if g is None:
                g = self._guards[name] = RecompileGuard(name)
            return g

    def report(self):
        """{name: {calls, traces, signatures}} for every registered
        guard, retrace-heaviest first."""
        with self._lock:
            guards = list(self._guards.values())
        return {g.name: g.snapshot() for g in
                sorted(guards, key=lambda g: -g.traces)}

    def reset(self):
        with self._lock:
            self._guards.clear()


registry = RecompileRegistry()


def track_lru(name):
    """Register an ``functools.lru_cache``-of-jits builder with the
    recompile registry: every cache miss (= a new jitted program) counts
    as a trace.  Stacks ABOVE the lru_cache decorator::

        @track_lru("parallel._moe_fn")
        @functools.lru_cache(maxsize=32)
        def _moe_fn(mesh, axis, top_k): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            misses = fn.cache_info().misses
            out = fn(*args, **kwargs)
            if fn.cache_info().misses > misses:
                sig = tuple(
                    (str(i), (str(a)[:120],))
                    for i, a in enumerate(args)
                ) + tuple(sorted(
                    (k, (str(v)[:120],)) for k, v in kwargs.items()))
                # force=True: an lru eviction rebuild is a real retrace
                registry.guard(name).observe(sig, force=True)
            return out

        wrapper.cache_info = fn.cache_info
        wrapper.cache_clear = fn.cache_clear
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# artifact
# ---------------------------------------------------------------------------

ARTIFACT_KIND = "mxnet_tpu-compile-report"


def report():
    """The full compile-time picture of this process: persistent-cache
    stats, the recompile registry, every recorded compile event, and
    the autotune knob applications the build ran under."""
    from . import profiler

    try:
        from . import autotune as _autotune

        tuned = _autotune.provenance()
    except ImportError:
        tuned = []
    return {
        "kind": ARTIFACT_KIND,
        "pid": os.getpid(),
        "time": time.time(),
        "cache": cache_stats(),
        "recompiles": registry.report(),
        "compile_events": profiler.compile_events(),
        "autotune": tuned,
    }


def write_artifact(path=None):
    """Write the compile report as JSON (pretty-print it with
    ``tools/compile_report.py``).  Default location follows the health
    artifacts: ``$MXNET_HEALTH_DIR`` or the tmpdir."""
    if path is None:
        base_dir = get_env("MXNET_HEALTH_DIR", "", str) or \
            tempfile.gettempdir()
        path = os.path.join(
            base_dir, "compile-report-%d-%d.json"
            % (os.getpid(), int(time.time())))
    payload = report()
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, default=repr)
    return path


def _reset_for_tests():
    """Test hook: forget initialization and zero the counters (the jax
    config side is left as-is — re-init just re-applies it)."""
    with _lock:
        _state.update(initialized=False, enabled=False, dir=None,
                      max_bytes=None, hits=0, requests=0, evictions=0,
                      evicted_bytes=0)
    registry.reset()
