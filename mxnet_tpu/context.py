"""Device context.

Replaces the reference's ``python/mxnet/context.py`` (``Context``,
``mx.cpu()``/``mx.gpu()``, thread-local default).  The TPU build adds
``mx.tpu()`` as the accelerator context — the north-star API from
BASELINE.json — and maps a context to a concrete ``jax.Device``.

Unlike the reference (where a context selects a CUDA device and a worker
thread pool, ``src/engine/threaded_engine_perdevice.cc``), here a context
selects a JAX device for ``jax.device_put`` / compilation targets; XLA owns
streams and async dispatch.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context"]


class Context:
    """Device context, API-compatible with the reference ``Context``
    (``python/mxnet/context.py:23``): ``devtype2mask``-style device types,
    equality, ``with ctx:`` default scoping."""

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 4: "gpu"}
    devstr2type = {"cpu": 1, "tpu": 2, "cpu_pinned": 3, "gpu": 4}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX mapping ---------------------------------------------------
    @property
    def jax_device(self):
        """The concrete ``jax.Device`` this context denotes.

        Always a process-LOCAL (addressable) device: under multi-process
        ``jax.distributed``, ``jax.devices()`` is the global list and
        ``device_put`` onto another process's device would silently
        create a non-addressable global array (reference semantics: a
        Context names a device of THIS worker)."""
        import jax

        # first device lookup doubles as the lazy hook for the
        # persistent compilation cache: anything about to jit resolves a
        # device first, so the cache config lands before the first trace
        from .compile_cache import ensure_initialized

        ensure_initialized()
        kind = self.device_type
        if kind in ("cpu", "cpu_pinned"):
            devs = jax.local_devices(backend="cpu") if _has_platform("cpu") \
                else jax.local_devices()
        else:
            # tpu (and gpu, aliased to the accelerator) → default platform
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


def _has_platform(name):
    import jax

    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def cpu(device_id=0):
    """A CPU context (reference ``mx.cpu()``)."""
    return Context("cpu", device_id)


def tpu(device_id=0):
    """A TPU context — the accelerator context of this framework
    (the ``mx.tpu()`` from the north star in BASELINE.json)."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Compatibility alias: reference scripts that say ``mx.gpu(i)`` get the
    accelerator (TPU) so `--gpus` scripts run unmodified."""
    return Context("tpu", device_id)


def current_context():
    """The thread-local default context (reference ``current_context()``)."""
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        ctx = Context("tpu", 0) if _accelerator_present() else Context("cpu", 0)
        Context._default_ctx.value = ctx
    return ctx


def _accelerator_present():
    import jax

    return jax.default_backend() not in ("cpu",)
