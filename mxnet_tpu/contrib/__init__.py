"""Experimental contributions (reference ``python/mxnet/contrib/``).

``mx.contrib.ndarray`` / ``mx.contrib.symbol`` expose the contrib op pack
(``_contrib_*`` registry entries — SSD MultiBox*, Proposal, deformable ops,
CTC, fft, quantize, khatri_rao; see ``mxnet_tpu/ops/contrib_ops.py``) under
their short names, mirroring how the reference filters registry names by
the ``_contrib_`` prefix at import.
"""
from . import ndarray
from . import symbol
from . import ndarray as nd
from . import symbol as sym
from . import autograd
from . import tensorboard
