"""Old-style contrib autograd API (reference
``python/mxnet/contrib/autograd.py`` — the pre-Gluon surface:
``train_section``/``test_section`` scopes, ``mark_variables``,
``compute_gradient``, and the ``grad``/``grad_and_loss`` decorators).
Implemented over the main :mod:`mxnet_tpu.autograd` tape.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from .. import autograd as _ag
from ..ndarray import NDArray, zeros_like

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Legacy global switch: returns the previous value."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    return prev


def train_section():
    """``with train_section():`` — record with train mode on (reference
    ``contrib/autograd.py:74``)."""
    return _ag.record(train_mode=True)


def test_section():
    """``with test_section():`` — pause recording (reference ``:88``)."""
    return _ag.pause(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    _ag.backward(outputs, head_grads=out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """Deprecated alias of :func:`backward` (reference ``:166``)."""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator: returns ``(gradients, loss)`` of ``func`` w.r.t. its
    NDArray arguments (reference ``:171``)."""

    @functools.wraps(func)
    def wrapped(*args):
        idx = range(len(args)) if argnum is None else (
            [argnum] if isinstance(argnum, int) else list(argnum))
        variables = [args[i] for i in idx]
        for v in variables:
            if not isinstance(v, NDArray):
                raise MXNetError("differentiated argument must be NDArray")
        grads = [zeros_like(v) for v in variables]
        mark_variables(variables, grads)
        with train_section():
            out = func(*args)
        backward([out] if isinstance(out, NDArray) else out)
        return grads, out

    return wrapped


def grad(func, argnum=None):
    """Decorator: returns only the gradients (reference ``:203``)."""
    g_and_l = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return g_and_l(*args)[0]

    return wrapped
