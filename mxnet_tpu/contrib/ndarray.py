"""``mx.contrib.nd`` — contrib ops, imperative (reference
``python/mxnet/contrib/ndarray.py``, generated from the ``_contrib_``
registry prefix)."""
from __future__ import annotations

import sys as _sys

from .. import ndarray as _nd


def _init():
    mod = _sys.modules[__name__]
    for name in dir(_nd):
        if name.startswith("_contrib_"):
            setattr(mod, name[len("_contrib_"):], getattr(_nd, name))


_init()
