"""``mx.contrib.sym`` — contrib ops, symbolic (reference
``python/mxnet/contrib/symbol.py``)."""
from __future__ import annotations

import sys as _sys

from .. import symbol as _sym


def _init():
    mod = _sys.modules[__name__]
    for name in dir(_sym):
        if name.startswith("_contrib_"):
            setattr(mod, name[len("_contrib_"):], getattr(_sym, name))


_init()
