"""TensorBoard logging callback (reference
``python/mxnet/contrib/tensorboard.py``).

The reference depends on the external ``tensorboard`` pip package's
``SummaryWriter``; this build is zero-egress, so the writer is pluggable:
anything with ``add_scalar(tag, value)`` works (e.g.
``torch.utils.tensorboard.SummaryWriter``, which IS available in this
image, or a test double).
"""
from __future__ import annotations

import logging

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Batch-end callback that logs ``eval_metric`` values
    (reference ``tensorboard.py:25``)::

        mod.fit(..., batch_end_callback=LogMetricsCallback('logs/train'))
    """

    def __init__(self, logging_dir, prefix=None, summary_writer=None):
        self.prefix = prefix
        if summary_writer is not None:
            self.summary_writer = summary_writer
        else:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self.summary_writer = SummaryWriter(logging_dir)
            except Exception:
                logging.getLogger(__name__).warning(
                    "no SummaryWriter backend available; metrics will be "
                    "dropped (pass summary_writer= explicitly)")
                self.summary_writer = None

    def __call__(self, param):
        if param.eval_metric is None or self.summary_writer is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value)
