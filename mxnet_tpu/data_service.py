"""Sharded deterministic data service: multiprocess decode behind the
device-staging ring.

Reference: the C++ ``ImageRecordIter`` (``src/io/iter_image_recordio_2.cc``
— sharded multithreaded decode into a ``dmlc::ThreadedIter`` double
buffer).  Python threads cannot reproduce its decode throughput (JPEG
decode only partially releases the GIL, augmentation not at all), so the
TPU build shards the decode across *processes* instead, and shards the
shuffle across *hosts* — while keeping the emitted sample stream a pure
function of ``(seed, epoch)``:

* **Global shuffle, strided sharding.**  Every host builds the same
  full-dataset permutation ``epoch_permutation(seed, epoch, n)`` from the
  one shared seed and takes its ``rank::nproc`` stride.  Sample ``m`` of
  global batch ``b`` is ``perm[b*G + m]`` (``G`` = global batch size)
  regardless of how many processes split the work, so the *global* sample
  sequence is identical at any process count — the property elastic
  N-proc save → M-proc resume needs.

* **Deterministic decode, any worker count.**  Workers receive
  ``(epoch, batch_id, sample indices)`` tasks, seed their per-sample RNGs
  from ``fold_in(seed, epoch, index)``, and the consumer reorders results
  by batch id — so worker completion order, worker count (including 0 =
  inline decode), and process start method never change the stream.

* **O(1) seek.**  ``seek(epoch, nbatch)`` recomputes the permutation for
  ``epoch`` and moves the cursor; nothing is replayed.  With a recordio-
  backed loader the per-sample jump is the ``.idx`` offset lookup.

Fault sites (``MXNET_FAULT_INJECT``): ``data_decode`` fires inside each
decode task (``raise`` surfaces as a typed error at the consumer's
``next()``; ``kill`` hard-exits the worker process so the consumer-side
dead-worker detection must fire; ``delay`` models slow decode; hits are
counted per worker process), ``data_service`` fires at the consumer's
``next()``.
"""
from __future__ import annotations

import os
import queue as pyqueue
import random as pyrandom
import threading
import time
import traceback

import numpy as np

from .base import MXNetError, get_env
from .io import DataBatch, DataDesc, DataIter

__all__ = ["fold_in", "epoch_permutation", "seed_sample",
           "DataServiceIter"]

_MASK64 = (1 << 64) - 1


def fold_in(seed, *vals):
    """Mix ``seed`` with integer counters into a 64-bit key (splitmix64
    finalizer per value).  Pure function: every host computes the same
    key for the same ``(seed, epoch, index)`` — the substrate for both
    the epoch permutation and per-sample augmentation RNG."""
    h = (int(seed) ^ 0x9E3779B97F4A7C15) & _MASK64
    for v in vals:
        h = (h + 0x9E3779B97F4A7C15 + (int(v) & _MASK64)) & _MASK64
        h = ((h ^ (h >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        h = ((h ^ (h >> 27)) * 0x94D049BB133111EB) & _MASK64
        h = h ^ (h >> 31)
    return h


def epoch_permutation(seed, epoch, n):
    """The full-dataset permutation for ``epoch`` — identical on every
    host (counter-based Philox keyed by ``fold_in(seed, epoch)``, so no
    sequential RNG state leaks between epochs or hosts)."""
    key = fold_in(seed, epoch)
    return np.random.Generator(np.random.Philox(key=key)).permutation(int(n))


def seed_sample(seed, epoch, index):
    """Seed the process-local ``random`` and ``np.random`` streams for
    one sample, so augmentation draws depend only on
    ``(seed, epoch, index)`` — not on which worker decodes the sample or
    what it decoded before."""
    m = fold_in(seed, epoch, index)
    # the sanctioned fold_in seeding site: global state is re-derived
    # from (seed, epoch, index) immediately before every sample
    pyrandom.seed(m)  # mxlint: disable=MX003
    np.random.seed(m & 0xFFFFFFFF)  # mxlint: disable=MX003


class _RemoteError:
    """Picklable carrier for a worker-side exception (tracebacks do not
    pickle; the string form crosses the process boundary instead)."""

    def __init__(self, exc):
        self.type_name = type(exc).__name__
        self.message = str(exc)
        self.traceback = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))

    def to_error(self):
        from .testing.faults import FaultInjected

        cls = FaultInjected if self.type_name == "FaultInjected" \
            else MXNetError
        return cls("data service decode worker failed: %s: %s\n%s"
                   % (self.type_name, self.message, self.traceback))


def _decode_batch(loader, seed, epoch, indices):
    """Decode one batch of global sample ``indices`` — shared by worker
    processes and the inline (``num_workers=0``) path, so both produce
    bit-identical results."""
    from .testing import faults

    faults.inject("data_decode")
    imgs, labels = [], []
    for i in indices:
        seed_sample(seed, epoch, int(i))
        img, lab = loader(int(i))
        imgs.append(np.asarray(img))
        labels.append(np.asarray(lab, np.float32))
    return np.stack(imgs), np.stack(labels)


def _decode_worker(loader, seed, task_q, result_q):
    """Decode worker main loop.  Tasks are ``(gen, bid, epoch, indices)``;
    ``None`` is the shutdown sentinel.  Results are ``(gen, bid, payload)``
    where payload is the decoded pair or a :class:`_RemoteError`."""
    from .testing import faults

    # a fork can capture the module lock mid-acquire in some parent
    # thread; replace it so the child cannot deadlock on it
    faults.rearm_after_fork()
    init = getattr(loader, "worker_init", None)
    if init is not None:
        init()  # e.g. re-open recordio privately (fork shares the offset)
    while True:
        task = task_q.get()
        if task is None:
            return
        gen, bid, epoch, indices = task
        try:
            payload = _decode_batch(loader, seed, epoch, indices)
        except faults.WorkerKilled:
            os._exit(17)  # hard death: no result, no sentinel
        except BaseException as exc:
            payload = _RemoteError(exc)
        result_q.put((gen, bid, payload))


class DataServiceIter(DataIter):
    """Deterministic sharded iterator over a picklable sample loader.

    ``loader`` maps a global sample index to ``(array, label)`` — e.g.
    :class:`~mxnet_tpu.image.RecordImageLoader` — and is pickled into
    ``num_workers`` decode processes (0 = decode inline on the consumer
    thread, same stream).  Optional loader attributes steer batch
    assembly: ``fast``/``tail_mean``/``tail_std`` (uint8 HWC samples
    finished by the jitted device tail), ``sample_shape``, ``label_width``,
    ``data_name``/``label_name``.

    This host emits batches ``order[b*bs:(b+1)*bs]`` of
    ``order = epoch_permutation(seed, epoch, n)[rank::nproc]``; partial
    trailing global batches are dropped so every host agrees on
    ``steps_per_epoch``.  ``reset()`` advances to the next epoch (the
    convention ``fit`` replays); ``seek(epoch, nbatch)`` jumps anywhere
    in O(1).
    """

    def __init__(self, loader, batch_size, num_samples=None, seed=None,
                 shuffle=True, num_workers=None, rank=None, nproc=None,
                 inflight=None, start_method=None, poll_s=0.2,
                 timeout_s=None):
        super().__init__(batch_size)
        self._loader = loader
        self._num_samples = int(num_samples if num_samples is not None
                                else len(loader))
        self._seed = int(seed if seed is not None
                         else get_env("MXNET_DATA_SEED", 0, int))
        self.shuffle = shuffle
        self._num_workers = int(num_workers if num_workers is not None
                                else get_env("MXNET_DATA_WORKERS", 0, int))
        self._rank = int(rank if rank is not None
                         else os.environ.get("MXNET_WORKER_ID", "0"))
        self._nproc = int(nproc if nproc is not None
                          else os.environ.get("MXNET_NUM_WORKERS", "1"))
        if self._nproc < 1 or not 0 <= self._rank < self._nproc:
            raise MXNetError("invalid rank %d / nproc %d"
                             % (self._rank, self._nproc))
        self._steps = self._num_samples // (batch_size * self._nproc)
        if self._steps < 1:
            raise MXNetError(
                "num_samples %d < one global batch (%d x %d procs)"
                % (self._num_samples, batch_size, self._nproc))
        self._inflight = int(inflight if inflight is not None
                             else get_env("MXNET_DATA_INFLIGHT",
                                          max(2, 2 * self._num_workers),
                                          int))
        self._start_method = start_method or get_env(
            "MXNET_DATA_START_METHOD", "fork", str)
        self._poll_s = float(poll_s)
        self._timeout_s = float(timeout_s if timeout_s is not None
                                else get_env("MXNET_DATA_TIMEOUT_S", 0.0,
                                             float))
        self._label_width = int(getattr(loader, "label_width", 1))
        self._data_name = getattr(loader, "data_name", "data")
        self._label_name = getattr(loader, "label_name", "softmax_label")
        self._sample_shape = tuple(getattr(loader, "sample_shape", ()))
        self._fast = bool(getattr(loader, "fast", False))
        self._tail_mean = getattr(loader, "tail_mean", None)
        self._tail_std = getattr(loader, "tail_std", None)
        self._epoch = 0
        self._cursor = 0     # next batch id to emit
        self._issued = 0     # next batch id to submit to the pool
        self._gen = 0        # bumped by seek: stale in-flight results drop
        self._order = None
        self._order_epoch = None
        self._results = {}   # (gen, bid) -> payload, reorder buffer
        self._error = None
        self._closed = False
        self._procs = []
        self._task_q = None
        self._result_q = None
        self._ensure_workers()
        self._submit_window()

    # -- provide_* -------------------------------------------------------
    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._sample_shape,
                         np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 else \
            (self.batch_size, self._label_width)
        return [DataDesc(self._label_name, shape, np.float32)]

    @property
    def steps_per_epoch(self):
        return self._steps

    # -- deterministic order --------------------------------------------
    def _epoch_order(self):
        if self._order is None or self._order_epoch != self._epoch:
            if self.shuffle:
                perm = epoch_permutation(self._seed, self._epoch,
                                         self._num_samples)
            else:
                perm = np.arange(self._num_samples)
            self._order = perm[self._rank::self._nproc]
            self._order_epoch = self._epoch
        return self._order

    def _batch_indices(self, bid):
        order = self._epoch_order()
        return order[bid * self.batch_size:(bid + 1) * self.batch_size]

    # -- worker pool -----------------------------------------------------
    def _ensure_workers(self):
        if self._num_workers <= 0 or self._procs:
            return
        import multiprocessing as mp

        ctx = mp.get_context(self._start_method)
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_decode_worker,
                        args=(self._loader, self._seed, self._task_q,
                              self._result_q),
                        name="mxtpu-data-worker-%d" % i, daemon=True)
            for i in range(self._num_workers)]
        for p in self._procs:
            p.start()

    def _submit_window(self):
        if not self._procs:
            return
        while self._issued < self._steps and \
                self._issued - self._cursor < self._inflight:
            self._task_q.put((self._gen, self._issued, self._epoch,
                              self._batch_indices(self._issued)))
            self._issued += 1

    def _check_workers(self, bid):
        dead = [p for p in self._procs
                if not p.is_alive() and p.exitcode not in (0, None)]
        if not dead and any(p.is_alive() for p in self._procs):
            return
        p = dead[0] if dead else self._procs[0]
        err = MXNetError(
            "data service decode worker %s died (exit code %s) without "
            "delivering batch %d; the input pipeline is broken (worker "
            "crashed or was killed)" % (p.name, p.exitcode, bid))
        self._error = err
        raise err

    def _collect(self, bid):
        """Block until batch ``bid`` of the current generation arrives,
        buffering out-of-order results and dropping stale-generation ones
        (pre-seek leftovers).  Poll-with-liveness instead of a blocking
        get: a dead worker must surface as a typed error, not a hang."""
        key = (self._gen, bid)
        deadline = (time.monotonic() + self._timeout_s) \
            if self._timeout_s > 0 else None
        while key not in self._results:
            try:
                g, b, payload = self._result_q.get(timeout=self._poll_s)
            except pyqueue.Empty:
                self._check_workers(bid)
                if deadline is not None and time.monotonic() > deadline:
                    err = MXNetError(
                        "data service timed out after %.1fs waiting for "
                        "batch %d (MXNET_DATA_TIMEOUT_S)"
                        % (self._timeout_s, bid))
                    self._error = err
                    raise err
                continue
            if g != self._gen:
                continue
            self._results[(g, b)] = payload
        return self._results.pop(key)

    # -- batch assembly --------------------------------------------------
    def _assemble(self, data, labels, indices):
        from .ndarray import NDArray, array

        bs = self.batch_size
        labels = labels.reshape(bs, -1)
        labels = labels[:, 0] if self._label_width == 1 else labels
        if self._fast:
            from .image import _batch_tail_fn

            dev = array(np.ascontiguousarray(data))
            out = _batch_tail_fn(self._tail_mean, self._tail_std)(dev._data)
            data_nd = NDArray(out, dev.context)
        else:
            data_nd = array(data.astype(np.float32, copy=False))
        return DataBatch(data=[data_nd], label=[array(labels)], pad=0,
                         index=np.asarray(indices),
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    # -- iteration -------------------------------------------------------
    def next(self):
        from .testing import faults

        faults.inject("data_service")
        if self._error is not None:
            raise self._error  # dead pipeline stays dead until seek/reset
        if self._closed or self._cursor >= self._steps:
            raise StopIteration
        bid = self._cursor
        indices = self._batch_indices(bid)
        if self._procs:
            self._submit_window()
            payload = self._collect(bid)
            if isinstance(payload, _RemoteError):
                err = payload.to_error()
                self._error = err
                raise err
            data, labels = payload
        else:
            data, labels = _decode_batch(self._loader, self._seed,
                                         self._epoch, indices)
        self._cursor += 1
        self._submit_window()
        return self._assemble(data, labels, indices)

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            self._next_batch = None
            return False

    def getdata(self):
        return self._next_batch.data

    def getlabel(self):
        return self._next_batch.label

    def getindex(self):
        return self._next_batch.index

    def getpad(self):
        return 0

    # -- positioning -----------------------------------------------------
    def seekable(self):
        return True

    def position(self):
        """``(epoch, next_batch)`` cursor — the quiesce-boundary record
        of the elastic migration: ``seek()`` back to exactly this pair
        resumes the stream bit-identically.  ``next_batch ==
        steps_per_epoch`` is the legal epoch-final boundary: the next
        ``next()`` raises StopIteration and the training loop rolls to
        the following epoch."""
        return (int(self._epoch), int(self._cursor))

    def seek(self, epoch, nbatch):
        """Jump to absolute position ``(epoch, nbatch)`` in O(1): bump the
        generation (in-flight results from the old position are dropped
        on arrival), recompute the epoch order lazily, and refill the
        submission window from the new cursor.  ``nbatch`` may equal
        ``steps_per_epoch`` — the epoch-final batch boundary — in which
        case the stream is immediately exhausted and the resume
        fast-forward rolls to the next epoch (the ``fit`` epoch-head
        StopIteration contract)."""
        epoch, nbatch = int(epoch), int(nbatch)
        if nbatch < 0 or nbatch > self._steps:
            raise MXNetError("seek nbatch %d out of range [0, %d]"
                             % (nbatch, self._steps))
        self._gen += 1
        self._results.clear()
        self._error = None
        self._closed = False
        self._epoch = epoch
        self._cursor = nbatch
        self._issued = nbatch
        self._ensure_workers()
        self._submit_window()

    def reset(self):
        """Advance to the next epoch — the same "one reset per epoch"
        contract ``fit`` and the O(steps) replay resume path assume."""
        self.seek(self._epoch + 1, 0)

    # -- teardown --------------------------------------------------------
    def close(self, timeout=5):
        """Shut the worker pool down deterministically: sentinels, join
        with ``timeout``, terminate stragglers, release the queues.  The
        iterator reports exhaustion until ``seek``/``reset`` (which
        respawn the pool)."""
        procs, self._procs = self._procs, []
        if procs:
            for _ in procs:
                try:
                    self._task_q.put_nowait(None)
                except Exception:
                    pass
            deadline = time.monotonic() + timeout
            for p in procs:
                p.join(timeout=max(0.0, deadline - time.monotonic()))
            for p in procs:
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=1)
            for q in (self._task_q, self._result_q):
                try:
                    q.close()
                    q.cancel_join_thread()
                except Exception:
                    pass
            self._task_q = self._result_q = None
        self._results.clear()
        self._closed = True

    def __del__(self):
        try:
            if self._procs:
                for p in self._procs:
                    p.terminate()
        except Exception:
            pass
