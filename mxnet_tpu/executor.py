"""Executor — a bound Symbol compiled to XLA.

TPU-native replacement for the reference ``GraphExecutor``
(``src/executor/graph_executor.cc``, Python ``python/mxnet/executor.py``).

Where the reference runs nnvm passes (Gradient, PlanMemory, inplace
detection, op-exec attach) and pushes one cached engine op per node
(``InitCachedOps``, ``graph_executor.cc:1186``), this executor traces the
whole symbol DAG into **one jitted XLA computation** per (is_train, shapes)
— forward, and a fused forward+backward built with ``jax.vjp``.  XLA's
buffer assignment and rematerialization replace PlanMemory and the
``MXNET_BACKWARD_DO_MIRROR`` mirror pass; bulk-exec segments are moot since
the whole graph is a single executable (SURVEY.md §7 item 5).

The ``Forward``/``Backward`` split API is preserved: ``forward(is_train=
True)`` runs a jitted program that also produces the vjp (residuals =
saved activations); ``backward`` applies the cached vjp seeded with head
gradients — no forward recompute — and scatters into the grad arrays
honoring ``grad_req`` (write/add/null — reference
``kWriteTo/kAddTo/kNullOp``).
"""
from __future__ import annotations

from collections import OrderedDict

from .base import MXNetError
from .ops import registry as _registry
from . import random as _random

__all__ = ["Executor"]


def _trace_fn(sym, is_train, node_hook=None):
    """Build the pure function (args, aux, rng) -> (outputs, new_aux).

    ``node_hook(node_name, out_idx, value)``, when given, fires for every
    node output — the per-node visibility the reference gets from
    ``ExecuteMonCallback``.  Hooked functions are for EAGER execution
    (monitor / NaiveEngine debug mode), not for jitting.
    """
    import jax

    topo = sym._topo()
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    aux_set = set(aux_names)
    out_refs = [(id(n), i) for (n, i) in sym._outputs]

    # positions of aux-updating results: node -> list of (input var name)
    def fn(args, aux, rng):
        from . import quantize as _quantize

        fp8_label = _quantize.fp8_tracing()
        env = {}
        new_aux = dict(aux)
        rng_i = 0
        for node in topo:
            if node.is_variable:
                if node.name in aux_set:
                    env[(id(node), 0)] = aux[node.name]
                else:
                    env[(id(node), 0)] = args[node.name]
                continue
            ins = [env[(id(src), i)] for (src, i) in node.inputs]
            attrs = dict(node.attrs)
            if node.op.uses_train_mode:
                attrs["__is_train__"] = is_train
            if fp8_label:
                # label fp8 matmul sites by node so MXNET_FP8_LAYERS
                # can name them; only under an active fp8 trace, so
                # clean traces keep byte-identical attrs
                attrs["__node_name__"] = node.name
            if node.op.needs_rng:
                ins = [jax.random.fold_in(rng, rng_i)] + ins
                rng_i += 1
            res = node.op.compute(_registry.FrozenAttrs(attrs), *ins)
            if not isinstance(res, tuple):
                res = (res,)
            n_out = node.num_outputs
            for i in range(n_out):
                env[(id(node), i)] = res[i]
                if node_hook is not None:
                    node_hook(node.name, i, res[i])
            # functional aux-state update (reference FMutateInputs)
            for mi, upd in zip(node.op.mutable_inputs, res[n_out:]):
                src, _ = node.inputs[mi]
                if src.is_variable and src.name in aux_set:
                    new_aux[src.name] = upd
        outputs = tuple(env[ref] for ref in out_refs)
        return outputs, new_aux

    return fn, arg_names, aux_names


class Executor:
    """Executor returned by ``Symbol.bind``/``simple_bind``."""

    def __init__(self, sym, ctx, arg_dict, grad_dict, grad_req, aux_dict):
        import jax

        from .compile_cache import ensure_initialized, registry

        ensure_initialized()
        self._symbol = sym
        self._ctx = ctx
        self.arg_dict = arg_dict          # OrderedDict name -> NDArray
        self.grad_dict = grad_dict        # name -> NDArray (or None)
        self.aux_dict = aux_dict
        self._grad_req = grad_req         # name -> str
        self.outputs = []
        self._monitor_callback = None
        self._monitor_all = False

        self._fwd_eval_fn, self._arg_names, self._aux_names = \
            _trace_fn(sym, is_train=False)
        self._fwd_train_fn, _, _ = _trace_fn(sym, is_train=True)

        self._jit_eval = jax.jit(self._fwd_eval_fn)
        self._jit_train = jax.jit(self._fwd_train_fn)

        grad_args = [n for n in self._arg_names
                     if grad_req.get(n, "null") != "null"]
        self._grad_args = grad_args

        # Training forward computes the outputs AND the vjp in one pass;
        # the vjp is a jax Partial pytree (residual arrays + static
        # closed jaxpr) that crosses the jit boundary, so ``backward``
        # applies it WITHOUT re-running the forward — the analogue of the
        # reference's cached fwd+bwd graph (``InitCachedOps``) with the
        # residuals playing the role of the saved activations.
        def fwd_vjp(args, aux, rng):
            const_args = {n: v for n, v in args.items() if n not in grad_args}

            def run(garg_vals):
                full = dict(const_args)
                full.update(garg_vals)
                return self._fwd_train_fn(full, aux, rng)

            gvals = {n: args[n] for n in grad_args}
            (outs, new_aux), vjp = jax.vjp(run, gvals)
            return outs, new_aux, vjp

        def bwd(vjp, head_grads, new_aux):
            grads, = vjp((head_grads, jax.tree.map(
                lambda x: jax.numpy.zeros_like(x), new_aux)))
            return grads

        self._jit_fwd_vjp = jax.jit(fwd_vjp)
        self._jit_bwd = jax.jit(bwd)
        # one guard covers all four jits; reusing the name means a
        # rebound/reshaped executor for the same symbol keeps
        # accumulating into the same counter (Executor.reshape storms
        # are exactly what the guard exists to surface)
        self._recompile_guard = registry.guard(
            "Executor(%s)" % (getattr(sym, "name", None) or "graph"))
        self._seen_sigs = set()
        self._last_vjp = None  # (vjp Partial, new_aux dict)
        # graphs holding a mesh-spanning program (shard_map, e.g.
        # seq_parallel attention) need inputs replicated over the mesh
        # rather than committed to this executor's single device
        self._spans_mesh = any(
            n.op is not None and n.op.spans_mesh is not None
            and n.op.spans_mesh(n.attrs) for n in sym._topo())

    # ------------------------------------------------------------------
    @property
    def arg_arrays(self):
        return list(self.arg_dict.values())

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return list(self.aux_dict.values())

    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        from .ndarray.ndarray import NDArray, array

        import jax

        dev = self._ctx.jax_device
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown forward input %r" % k)
            tgt = self.arg_dict[k]
            buf = v._data if isinstance(v, NDArray) else array(v)._data
            if buf.device != dev:
                buf = jax.device_put(buf, dev)
            tgt._set_data(buf)
        args = {n: a._data for n, a in self.arg_dict.items()}
        aux = {n: a._data for n, a in self.aux_dict.items()}
        if self._spans_mesh:
            from .parallel import current_mesh

            mesh = current_mesh()
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(mesh, PartitionSpec())
                args = {n: jax.device_put(a, repl)
                        for n, a in args.items()}
                aux = {n: jax.device_put(a, repl) for n, a in aux.items()}
        rng = _random.next_key()
        from .base import get_env

        if (self._monitor_callback is not None and self._monitor_all) or \
                get_env("MXNET_ENGINE_TYPE", "", str) == "NaiveEngine":
            # eager node-by-node interpretation: per-node monitor
            # visibility (reference ExecuteMonCallback) and the
            # NaiveEngine synchronous debug mode in one — each op runs
            # and materializes before the next
            return self._forward_eager(args, aux, rng, is_train)
        from .compile_cache import signature_of

        mode = ("fwd_vjp" if is_train and self._grad_args
                else "train" if is_train else "eval")
        sig = ((".mode", mode),) + signature_of(args, aux)
        # a freshly (re)bound executor retraces even for a signature the
        # guard has seen before (jits are per-instance) — force-count it
        self._recompile_guard.observe(sig, force=sig not in self._seen_sigs)
        self._seen_sigs.add(sig)
        if is_train and self._grad_args:
            # release the previous step's residuals before the new forward
            # (holding them would double peak activation memory)
            self._last_vjp = None
            outs, new_aux, vjp = self._jit_fwd_vjp(args, aux, rng)
            self._last_vjp = (vjp, new_aux)
        else:
            fn = self._jit_train if is_train else self._jit_eval
            outs, new_aux = fn(args, aux, rng)
            if is_train:
                self._train_fwd_ran = True
        if self._spans_mesh:
            # bring results back to this executor's device so downstream
            # imperative ops (metrics, updaters) see single-device arrays
            outs = tuple(jax.device_put(o, dev) for o in outs)
            new_aux = {n: jax.device_put(v, dev)
                       for n, v in new_aux.items()}
        if is_train:
            for n, v in new_aux.items():
                self.aux_dict[n]._set_data(v)
        from .ndarray.ndarray import NDArray as _ND

        self.outputs = [_ND(o, self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Compute gradients into ``grad_dict`` honoring grad_req.

        Applies the vjp cached by ``forward(is_train=True)`` — the
        forward is NOT re-run; the saved residuals are consumed exactly
        like the reference's backward over the cached fwd+bwd graph."""
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        if self._last_vjp is None:
            if not self._grad_args and getattr(self, "_train_fwd_ran",
                                               False):
                return  # all grad_req 'null': backward is a no-op (kNullOp)
            raise MXNetError("backward called before forward(is_train=True)")
        vjp, new_aux = self._last_vjp
        # head gradients: default ones (loss heads use their own custom vjp)
        out_shapes = [o._data for o in self.outputs]
        if out_grads is None:
            heads = tuple(jnp.ones_like(o) for o in out_shapes)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = tuple(
                jnp.ones_like(o) if g is None else
                (g._data if isinstance(g, NDArray) else jnp.asarray(g))
                for o, g in zip(out_shapes, out_grads))
        if self._spans_mesh:
            from .parallel import current_mesh

            mesh = current_mesh()
            if mesh is not None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(mesh, PartitionSpec())
                heads = tuple(jax.device_put(h, repl) for h in heads)
                new_aux = {n: jax.device_put(v, repl)
                           for n, v in new_aux.items()}
        grads = self._jit_bwd(vjp, heads, new_aux)
        if self._spans_mesh:
            import jax

            dev = self._ctx.jax_device
            grads = {n: jax.device_put(g, dev) for n, g in grads.items()}
        for n, g in grads.items():
            tgt = self.grad_dict.get(n)
            if tgt is None:
                continue
            if self._grad_req.get(n) == "add":
                tgt._set_data(tgt._data + g)
            else:
                tgt._set_data(g)

    def forward_backward(self, out_grads=None, **kwargs):
        self.forward(is_train=True, **kwargs)
        self.backward(out_grads)
        return self.outputs

    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown arg %r" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("unknown aux %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new shapes, sharing parameter arrays (reference
        ``Executor.reshape`` — used by BucketingModule/DataParallel)."""
        from .ndarray.ndarray import zeros

        new_shapes = {}
        for n, arr in self.arg_dict.items():
            new_shapes[n] = kwargs.get(n, arr.shape)
        ex = Executor._simple_bind(
            self._symbol, self._ctx,
            "null" if not self.grad_dict else self._grad_req, new_shapes)
        for n, arr in self.arg_dict.items():
            if ex.arg_dict[n].shape == arr.shape:
                ex.arg_dict[n] = arr
        for n, arr in self.aux_dict.items():
            ex.aux_dict[n] = arr
        return ex

    def _forward_eager(self, args, aux, rng, is_train):
        """Monitor / NaiveEngine path: run the graph eagerly, firing the
        monitor callback per node output, then fall through to the normal
        vjp caching so backward still works."""
        import jax

        from .ndarray.ndarray import NDArray as _ND

        cb = self._monitor_callback

        def hook(name, idx, value):
            if cb is not None:
                out_name = "%s_output%s" % (name, idx if idx else "")
                cb(out_name, _ND(value, self._ctx))

        fn, _, _ = _trace_fn(self._symbol, is_train=is_train,
                             node_hook=hook)
        outs, new_aux = fn(args, aux, rng)
        if is_train:
            for n, v in new_aux.items():
                self.aux_dict[n]._set_data(v)
            if self._grad_args:
                # cache the vjp for backward (the monitor pass above ran
                # eagerly; the vjp capture runs the jitted path once)
                self._last_vjp = None
                _, new_aux2, vjp = self._jit_fwd_vjp(args, aux, rng)
                self._last_vjp = (vjp, new_aux2)
            else:
                self._train_fwd_ran = True
        self.outputs = [_ND(o, self._ctx) for o in outs]
        return self.outputs

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback
        self._monitor_all = bool(monitor_all)

    @property
    def output_dict(self):
        return OrderedDict(zip(self._symbol.list_outputs(), self.outputs))

    # ------------------------------------------------------------------
    # binding constructors
    # ------------------------------------------------------------------
    @staticmethod
    def _simple_bind(sym, ctx, grad_req, shape_kwargs, shared_exec=None):
        from .context import current_context
        from .ndarray.ndarray import zeros
        from .symbol.symbol import _infer_param_shapes

        ctx = ctx or current_context()
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        shapes = _infer_param_shapes(sym, dict(shape_kwargs))
        missing = [n for n in arg_names + aux_names if n not in shapes]
        if missing:
            raise MXNetError("simple_bind: could not infer shapes for %s"
                             % missing)
        if isinstance(grad_req, str):
            # uniform req applies to parameters; data/label inputs (the
            # shape kwargs) get no gradient, as in the reference simple_bind
            grad_req = {n: grad_req for n in arg_names}
            for n in shape_kwargs:
                grad_req[n] = "null"
        elif isinstance(grad_req, list):
            grad_req = dict(zip(arg_names, grad_req))
        else:
            grad_req = dict(grad_req)
            for n in shape_kwargs:
                grad_req.setdefault(n, "null")

        arg_dict = OrderedDict()
        grad_dict = {}
        for n in arg_names:
            if shared_exec is not None and n in shared_exec.arg_dict and \
                    shared_exec.arg_dict[n].shape == tuple(shapes[n]):
                arg_dict[n] = shared_exec.arg_dict[n]
                if shared_exec.grad_dict.get(n) is not None:
                    grad_dict[n] = shared_exec.grad_dict[n]
            else:
                arg_dict[n] = zeros(shapes[n], ctx)
            if grad_req.get(n, "write") != "null" and n not in grad_dict:
                grad_dict[n] = zeros(shapes[n], ctx)
        aux_dict = OrderedDict()
        for n in aux_names:
            if shared_exec is not None and n in shared_exec.aux_dict:
                aux_dict[n] = shared_exec.aux_dict[n]
            else:
                aux_dict[n] = zeros(shapes[n], ctx)
        return Executor(sym, ctx, arg_dict, grad_dict, grad_req, aux_dict)

    @staticmethod
    def _bind(sym, ctx, args, args_grad, grad_req, aux_states,
              shared_exec=None):
        from .context import current_context

        ctx = ctx or current_context()
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            arg_dict = OrderedDict(zip(arg_names, args))
        else:
            arg_dict = OrderedDict((n, args[n]) for n in arg_names)
        if args_grad is None:
            grad_dict = {}
        elif isinstance(args_grad, (list, tuple)):
            grad_dict = {n: g for n, g in zip(arg_names, args_grad)
                         if g is not None}
        else:
            grad_dict = dict(args_grad)
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, list):
            grad_req = dict(zip(arg_names, grad_req))
        if aux_states is None:
            aux_dict = OrderedDict()
        elif isinstance(aux_states, (list, tuple)):
            aux_dict = OrderedDict(zip(aux_names, aux_states))
        else:
            aux_dict = OrderedDict((n, aux_states[n]) for n in aux_names)
        return Executor(sym, ctx, arg_dict, grad_dict, grad_req, aux_dict)
