"""Legacy multi-device executor manager (reference
``python/mxnet/executor_manager.py``: ``_split_input_slice`` ``:295`` and
``DataParallelExecutorManager``, used by the old ``FeedForward`` path).

TPU-native stance: the *modern* data-parallel path is the fused SPMD train
step (``mxnet_tpu/fused.py``) where the mesh shards the batch and XLA
inserts the collectives — ``Module``/``FeedForward`` use that.  This module
keeps the reference's explicit slice-per-context contract working for
scripts that drive it directly: each context gets an executor over its
batch slice, gradients are summed across slices host-side (the role of the
reference's kvstore ``local`` reduction), and parameters are shared.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .context import cpu

__all__ = ["_split_input_slice", "DataParallelExecutorManager",
           "pair_metric_outputs"]


def pair_metric_outputs(symbol, label_names, labels, outputs):
    """Pair metric labels with prediction heads when the symbol carries
    extra loss-only outputs (MakeLoss aux terms, e.g. a MoE load-balance
    loss).  Matching is by exact head name (``stem + '_output'``), never
    by prefix — ``softmax`` must not capture ``softmax2`` — and the
    positional fallback skips loss-only heads."""
    if len(outputs) <= len(labels):
        return outputs
    names = symbol.list_outputs()
    loss_only = set(getattr(symbol, "_makeloss_outputs", lambda: [])())
    pred_outputs = [o for n, o in zip(names, outputs) if n not in loss_only]
    picked = []
    for i, ln in enumerate(label_names[:len(labels)]):
        stem = ln[:-6] if ln.endswith("_label") else ln
        match = [o for n, o in zip(names, outputs)
                 if n == stem + "_output" or n == stem]
        if match:
            picked.append(match[0])
        elif i < len(pred_outputs):
            picked.append(pred_outputs[i])
        else:
            picked.append(outputs[i])
    return picked


def _split_input_slice(batch_size, work_load_list=None):
    """Split ``batch_size`` into per-device ``slice``s proportional to
    ``work_load_list`` (reference ``executor_manager.py:12-43``)."""
    if work_load_list is None:
        work_load_list = [1]
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size %d cannot cover %d devices"
                         % (batch_size, len(work_load_list)))
    slices = []
    start = 0
    accum = 0.0
    for i, w in enumerate(work_load_list):
        accum += float(w) / total * batch_size
        end = batch_size if i == len(work_load_list) - 1 \
            else int(round(accum))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorManager:
    """Per-context executors over batch slices sharing one parameter set
    (reference ``executor_manager.py:295``)."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else [ctx or cpu()]
        self.logger = logger or logging
        if work_load_list is None:
            work_load_list = [1] * len(self.ctx)
        if len(work_load_list) != len(self.ctx):
            raise MXNetError("work_load_list must match number of contexts")
        data_shapes = {d.name: d.shape for d in train_data.provide_data}
        label_shapes = {d.name: d.shape
                        for d in (train_data.provide_label or [])}
        batch_size = next(iter(data_shapes.values()))[0]
        self.slices = _split_input_slice(batch_size, work_load_list)
        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        data_like = set(data_shapes) | set(label_shapes)
        self.param_names = param_names or [
            n for n in self.arg_names if n not in data_like]
        self._data_names = list(data_shapes)
        self._label_names = list(label_shapes)

        self.execs = []
        for ctx_i, slc in zip(self.ctx, self.slices):
            n = slc.stop - slc.start
            shapes = {k: (n,) + tuple(v[1:]) for k, v in data_shapes.items()}
            shapes.update({k: (n,) + tuple(v[1:])
                           for k, v in label_shapes.items()})
            grad_req = {name: ("write" if name in self.param_names
                               else "null") for name in self.arg_names}
            # deliberately NOT shared_exec: each slice keeps its own grad
            # buffers (the reference reduces them via kvstore); parameters
            # are aliased to the master's arrays below
            ex = symbol.simple_bind(ctx=ctx_i, grad_req=grad_req, **shapes)
            self.execs.append(ex)
        # parameters are shared: slave executors view the master's arrays
        master = self.execs[0]
        for ex in self.execs[1:]:
            for name in self.param_names:
                ex.arg_dict[name] = master.arg_dict[name]
            for name in self.aux_names:
                ex.aux_dict[name] = master.aux_dict[name]
        self._monitor = None

    # -- parameter access (reference :364-392) -----------------------------
    @property
    def param_arrays(self):
        return [[self.execs[0].arg_dict[n]] for n in self.param_names]

    @property
    def grad_arrays(self):
        return [[ex.grad_dict[n] for ex in self.execs]
                for n in self.param_names]

    @property
    def aux_arrays(self):
        return [[self.execs[0].aux_dict[n]] for n in self.aux_names]

    def set_params(self, arg_params, aux_params):
        for ex in self.execs[:1]:
            ex.copy_params_from(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        master = self.execs[0]
        for name in self.param_names:
            arg_params[name] = master.arg_dict[name].copy()
        for name in self.aux_names:
            aux_params[name] = master.aux_dict[name].copy()

    def install_monitor(self, monitor):
        for ex in self.execs:
            monitor.install(ex)

    # -- the train loop surface (reference :398-430) -----------------------
    def load_data_batch(self, data_batch):
        self._cur_batch = data_batch

    def forward(self, is_train=False):
        data = {n: a for n, a in zip(self._data_names,
                                     self._cur_batch.data)}
        labels = {n: a for n, a in zip(self._label_names,
                                       self._cur_batch.label or [])}
        for ex, slc in zip(self.execs, self.slices):
            feeds = {k: v[slc.start:slc.stop] for k, v in data.items()}
            feeds.update({k: v[slc.start:slc.stop]
                          for k, v in labels.items()})
            ex.forward(is_train=is_train, **feeds)

    def backward(self):
        for ex in self.execs:
            ex.backward()

    def update_metric(self, metric, labels):
        for ex, slc in zip(self.execs, self.slices):
            lab = [l[slc.start:slc.stop] for l in labels]
            metric.update(lab, pair_metric_outputs(
                self.symbol, self._label_names, lab, ex.outputs))

    @property
    def outputs(self):
        from .ndarray import concat

        outs = []
        for i in range(len(self.execs[0].outputs)):
            parts = [ex.outputs[i] for ex in self.execs]
            outs.append(parts[0] if len(parts) == 1 else concat(
                *parts, dim=0))
        return outs
