"""Fused train step — the TPU performance path.

The reference's fastest path pushes per-node cached engine ops plus
separate optimizer-update ops (SURVEY.md §3.1, fused update ops in
``src/operator/optimizer_op.cc``).  On TPU the whole thing — forward,
backward, optimizer update, and (under a mesh) the gradient all-reduce —
compiles into ONE XLA program with donated parameter buffers: zero host
round-trips per step and maximal fusion (measured on the single real
chip).  Under a multi-chip mesh the single-program form additionally
lets XLA's scheduler overlap the gradient collectives with backward
compute — design intent pending real-ICI measurement (this environment
has one chip); the pod-side check is a profiler trace confirming
all-reduce slots hide under the backward convolutions
(docs/distributed.md "pending hardware" list).  This is what ``Module``
uses when ``fit`` runs with a compiled step, and what bench.py measures.

Any registered :class:`~mxnet_tpu.optimizer.Optimizer` that implements
``fused_update`` (all of the built-in family) compiles in; per-parameter
``lr_mult``/``wd_mult`` (symbol ``__lr_mult__``/``__wd_mult__`` attrs and
the no-decay-for-bias default) are honored exactly like the split
``Optimizer._get_lr/_get_wd`` path.

Extra TPU-first knobs the reference exposes differently:

* ``compute_dtype='bfloat16'`` — mixed precision: parameters stay fp32
  (master weights, the reference's ``mp_sgd_*`` contract) and are cast to
  bf16 for the forward/backward so matmuls/convs hit the MXU at full
  rate; gradients come back fp32 for the update.
* ``remat`` — gradient checkpointing (the reference's
  ``MXNET_BACKWARD_DO_MIRROR`` / ``__force_mirroring__``,
  ``src/executor/graph_executor.cc:273-296``): ``'full'`` recomputes all
  activations in the backward, or pass a named jax checkpoint policy
  (e.g. ``'dots_with_no_batch_dims_saveable'``).
* ``steps_per_call=K`` — multi-step dispatch: ``__call__`` takes a
  ``(K, batch, …)`` super-batch and ``lax.scan``s K donated updates in
  ONE device call, amortizing Python dispatch for small models (fed by
  ``io.DevicePrefetchIter(steps_per_call=K)``; see docs/performance.md).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["compile_train_step", "TrainStep"]


def _loss_from_outputs(outs):
    """Seed the backward exactly like Executor.backward with ones head
    grads: sum of outputs (loss heads carry custom vjp that ignores the
    cotangent's value)."""
    total = None
    for o in outs:
        s = o.astype("float32").sum()
        total = s if total is None else total + s
    return total


def _buffer_key(x):
    """Identity of the underlying device buffer (best effort)."""
    try:
        return ("ptr", x.unsafe_buffer_pointer())
    except Exception:
        return ("id", id(x))


def _resolve_remat(remat):
    import jax

    if remat is None or remat is False:
        return None
    if remat is True or remat == "full":
        return "full"
    if isinstance(remat, str):
        policy = getattr(jax.checkpoint_policies, remat, None)
        if policy is None:
            raise MXNetError("unknown remat policy %r" % remat)
        return policy
    return remat  # a jax checkpoint policy callable


class TrainStep:
    """Compiled (params, aux, opt_states, batch) -> updated state step."""

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_names=("data",),
                 label_names=("softmax_label",), dtype="float32",
                 batch_sharding_axis="data", compute_dtype=None,
                 remat=None, fixed_param_names=(), param_sharding=None,
                 steps_per_call=1):
        import jax
        import jax.numpy as jnp

        from .executor import _trace_fn
        from . import optimizer as opt_mod

        self.symbol = symbol
        self._fwd_fn, self._arg_names, self._aux_names = _trace_fn(
            symbol, is_train=True)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [n for n in self._arg_names
                            if n not in self.data_names
                            and n not in self.label_names]
        self.mesh = mesh

        opt_params = dict(optimizer_params or {})
        fixed = frozenset(fixed_param_names) | frozenset(
            opt_params.pop("fixed_param_names", ()))
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **opt_params)
        elif isinstance(optimizer, opt_mod.Optimizer):
            if opt_params:
                raise MXNetError(
                    "optimizer_params must not be set when passing an "
                    "Optimizer instance (got %r); configure the instance "
                    "instead" % sorted(opt_params))
        else:
            raise MXNetError("optimizer must be a name or Optimizer")
        if not optimizer.supports_fused:
            raise MXNetError("optimizer %s has no fused form"
                             % type(optimizer).__name__)
        self.optimizer = optimizer
        self.lr = optimizer.lr

        # static per-parameter multipliers, resolved by name exactly like
        # Optimizer._get_lr/_get_wd
        lr_mults = {n: optimizer.lr_mult.get(n, 1.0)
                    for n in self.param_names}
        wd_mults = {n: optimizer.wd_mult.get(n, 1.0)
                    for n in self.param_names}
        base_wd = optimizer.wd

        fwd_fn = self._fwd_fn
        remat_policy = _resolve_remat(remat)
        if remat_policy == "full":
            fwd_fn = jax.checkpoint(fwd_fn)
        elif remat_policy is not None:
            fwd_fn = jax.checkpoint(fwd_fn, policy=remat_policy)
        cdtype = compute_dtype
        self._compute_dtype = compute_dtype
        frozen = fixed

        def cast_compute(x):
            return x.astype(cdtype) if jnp.issubdtype(
                x.dtype, jnp.floating) else x

        def step(params, aux, states, batch, rng, lr, t):
            def loss_fn(p):
                args = dict(p)
                args.update(batch)
                a = aux
                if cdtype is not None:
                    args = {k: cast_compute(v) for k, v in args.items()}
                    a = {k: cast_compute(v) for k, v in aux.items()}
                outs, new_aux = fwd_fn(args, a, rng)
                if cdtype is not None:
                    new_aux = {k: v.astype(aux[k].dtype)
                               for k, v in new_aux.items()}
                return _loss_from_outputs(outs), (outs, new_aux)

            grads, (outs, new_aux) = jax.grad(
                loss_fn, has_aux=True)(params)
            new_params, new_states = {}, {}
            for i, k in enumerate(sorted(grads)):
                g = grads[k]
                if k in frozen:
                    new_params[k] = params[k]
                    new_states[k] = states[k]
                    continue
                new_params[k], new_states[k] = optimizer.fused_update(
                    params[k], g, states[k],
                    lr * lr_mults[k], base_wd * wd_mults[k], t,
                    jax.random.fold_in(rng, i + 1))
            # all outputs come back (multi-loss symbols run fused too);
            # a batch-sharded prefix sharding covers the whole tuple
            return new_params, new_aux, new_states, outs

        K = int(steps_per_call)
        if K < 1:
            raise MXNetError("steps_per_call must be >= 1, got %d" % K)
        self._steps_per_call = K
        if K > 1:
            # multi-step dispatch: one device call scans K donated
            # updates over a (K, batch, …) super-batch — Python dispatch
            # and launch overhead amortize K-fold (the win for small
            # models where per-step host work rivals device time).  lr is
            # held constant across the K inner steps (the scheduler is
            # consulted once per call); t advances per inner step so
            # bias-corrected optimizers stay exact; the per-call rng is
            # folded with the inner step index so dropout masks differ
            # per step.  Outputs come back stacked (K, batch, …).
            base_step = step

            def step(params, aux, states, batch, rng, lr, t):
                def body(carry, xs):
                    p, a, s, tk = carry
                    bk, k = xs
                    p, a, s, outs = base_step(
                        p, a, s, bk, jax.random.fold_in(rng, k), lr, tk)
                    return (p, a, s, tk + 1), outs

                (params, aux, states, _), outs = jax.lax.scan(
                    body, (params, aux, states, t),
                    (batch, jnp.arange(K)))
                return params, aux, states, outs

        self._step_fn = step
        self._batch_sharding_axis = batch_sharding_axis
        self._param_sharding = param_sharding
        if param_sharding not in (None, "replicated"):
            if mesh is None:
                raise MXNetError(
                    "param_sharding=%r needs a mesh (pass mesh=... or run "
                    "under a dist kvstore)" % (param_sharding,))
            if isinstance(param_sharding, str):
                # validate the style NOW: a typo must fail at construction
                # (inside Module's fused-fallback handling), not on the
                # first training batch
                from .parallel.sharding import param_sharding_rules

                param_sharding_rules(param_sharding)
        if mesh is not None and param_sharding not in (None, "replicated"):
            # FSDP's largest-dim rule needs concrete parameter SHAPES, so
            # the jitted step is built lazily on the first call
            self._jit_step = None
        elif mesh is not None:
            self._jit_step = self._build_jit()
        else:
            self._jit_step = jax.jit(step, donate_argnums=(0, 1, 2))
        self._t = 0

    def _build_jit(self, pshard=None, sshard=None):
        """jit the step with parameter/state shardings resolved.

        ``pshard``: {name: NamedSharding} (or None → replicate all);
        ``sshard``: a pytree prefix for the optimizer states (or None).
        Gradients need no annotation: GSPMD propagates shardings and
        inserts the collectives (all-gather for fsdp params,
        all-reduce/reduce-scatter for grads — the TPU form of the
        reference's push/pull).
        """
        import jax

        from .parallel.sharding import (batch_axes, named_sharding,
                                        replicated)

        mesh = self.mesh
        repl = replicated(mesh)
        # batch sharding mirrors shard_batch exactly (data axis plus
        # fsdp when present); pure SP/EP/pipe meshes carry no batch
        # axis, so the batch stays replicated and the mesh axes are
        # consumed inside the ops (ring attention, MoE all_to_all)
        baxes = batch_axes(mesh, self._batch_sharding_axis)
        # a packed super-batch carries an unsharded leading K axis; the
        # batch dim (and the stacked outputs' step dim) sits behind it
        lead = [None] if self._steps_per_call > 1 else []
        bshard = named_sharding(mesh, *(lead + [baxes])) if baxes else repl
        if pshard is None:
            pshard = repl
        if sshard is None:
            sshard = repl if not isinstance(pshard, dict) else pshard
        bdict = {n: bshard for n in self.data_names + self.label_names}
        return jax.jit(
            self._step_fn,
            in_shardings=(pshard, repl, sshard, bdict, repl, None, None),
            out_shardings=(pshard, repl, sshard, bshard),
            donate_argnums=(0, 1, 2))

    def _build_sharded_jit(self, params, states):
        """Resolve param_sharding rules against concrete shapes and jit.

        Optimizer state leaves follow their parameter's sharding when
        shaped like the weight (momentum/adam moments), else replicate
        (scalars, schedules) — the ZeRO contract that sharded params
        carry sharded optimizer states.
        """
        import jax

        from .parallel.sharding import (apply_rules, param_sharding_rules,
                                        replicated)

        rules = self._param_sharding
        if isinstance(rules, str):
            rules = param_sharding_rules(rules)
        pshard = apply_rules(self.mesh, params, rules)
        repl = replicated(self.mesh)
        sshard = {
            n: jax.tree.map(
                lambda leaf, _n=n: pshard[_n]
                if tuple(leaf.shape) == tuple(params[_n].shape) else repl,
                states[n])
            for n in states
        }
        self._in_pshard = pshard
        self._in_sshard = sshard
        return self._build_jit(pshard, sshard)

    def __call__(self, params, aux, states, batch, rng, lr=None, t=None):
        import jax
        import jax.numpy as jnp

        K = self._steps_per_call
        if t is None:
            self._t += K
            t = self._t - K + 1  # first inner step's post-increment count
        else:
            self._t = int(t) + K - 1
        # Two input hygiene passes before the donated call:
        # 1. commit uncommitted arrays (jnp.zeros products) so the jit
        #    signature is identical on every step — no recompiles;
        # 2. donated pytrees must not alias each other (some optimizers
        #    seed state from the weight buffer; XLA may also alias
        #    identical outputs) — copy duplicates.
        seen = set()

        def dedupe(x):
            if not getattr(x, "committed", True):
                x = jax.device_put(x, next(iter(x.devices())))
            k = _buffer_key(x)
            if k in seen:
                return jnp.copy(x)
            seen.add(k)
            return x

        params, aux, states = jax.tree.map(
            dedupe, (params, aux, states))
        if self._jit_step is None:
            self._jit_step = self._build_sharded_jit(params, states)
        if getattr(self, "_in_pshard", None) is not None:
            # committed single-device arrays cannot be auto-resharded to
            # a non-trivial layout by jit; place them explicitly (no-op
            # once the donated outputs carry the sharding)
            params = jax.device_put(params, self._in_pshard)
            states = jax.device_put(states, self._in_sshard)
        return self._jit_step(params, aux, states, batch, rng,
                              self.lr if lr is None else lr,
                              jnp.asarray(t, "int32"))

    def init_state(self, shapes, dtype="float32", seed=0):
        """Allocate params/aux/optimizer-states as raw jax arrays via the
        shape inference pass + Xavier-ish scaling (bench/profiling
        convenience; real training initializes through Module)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .symbol.symbol import _infer_param_shapes

        all_shapes = _infer_param_shapes(self.symbol, dict(shapes))
        key = jax.random.PRNGKey(seed)
        params, aux, states = {}, {}, {}
        for n in self.param_names:
            shp = all_shapes[n]
            key, sub = jax.random.split(key)
            if n.endswith(("_gamma",)):
                params[n] = jnp.ones(shp, dtype)
            elif n.endswith(("_bias", "_beta")):
                params[n] = jnp.zeros(shp, dtype)
            else:
                fan_in = int(np.prod(shp[1:])) if len(shp) > 1 else shp[0]
                scale = (2.0 / max(1, fan_in)) ** 0.5
                params[n] = scale * jax.random.normal(sub, shp, dtype)
            states[n] = self.optimizer.init_fused_state(params[n])
        for n in self._aux_names:
            shp = all_shapes[n]
            aux[n] = jnp.ones(shp, "float32") if n.endswith("_var") \
                else jnp.zeros(shp, "float32")
        return params, aux, states


def compile_train_step(symbol, **kwargs):
    return TrainStep(symbol, **kwargs)
