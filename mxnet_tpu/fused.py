"""Fused train step — the TPU performance path.

The reference's fastest path pushes per-node cached engine ops plus
separate optimizer-update ops (SURVEY.md §3.1, fused update ops in
``src/operator/optimizer_op.cc``).  On TPU the whole thing — forward,
backward, optimizer update, and (under a mesh) the gradient all-reduce —
compiles into ONE XLA program with donated parameter buffers: zero host
round-trips per step and maximal fusion (measured on the single real
chip).  Under a multi-chip mesh the single-program form additionally
lets XLA's scheduler overlap the gradient collectives with backward
compute — design intent pending real-ICI measurement (this environment
has one chip); the pod-side check is a profiler trace confirming
all-reduce slots hide under the backward convolutions
(docs/distributed.md "pending hardware" list).  This is what ``Module``
uses when ``fit`` runs with a compiled step, and what bench.py measures.

Any registered :class:`~mxnet_tpu.optimizer.Optimizer` that implements
``fused_update`` (all of the built-in family) compiles in; per-parameter
``lr_mult``/``wd_mult`` (symbol ``__lr_mult__``/``__wd_mult__`` attrs and
the no-decay-for-bias default) are honored exactly like the split
``Optimizer._get_lr/_get_wd`` path.

Extra TPU-first knobs the reference exposes differently:

* ``compute_dtype='bfloat16'`` — mixed precision: parameters stay fp32
  (master weights, the reference's ``mp_sgd_*`` contract) and are cast to
  bf16 for the forward/backward so matmuls/convs hit the MXU at full
  rate; gradients come back fp32 for the update.
* ``remat`` — gradient checkpointing (the reference's
  ``MXNET_BACKWARD_DO_MIRROR`` / ``__force_mirroring__``,
  ``src/executor/graph_executor.cc:273-296``): ``'full'`` recomputes all
  activations in the backward, or pass a named jax checkpoint policy
  (e.g. ``'dots_with_no_batch_dims_saveable'``).
* ``steps_per_call=K`` — multi-step dispatch: ``__call__`` takes a
  ``(K, batch, …)`` super-batch and ``lax.scan``s K donated updates in
  ONE device call, amortizing Python dispatch for small models (fed by
  ``io.DevicePrefetchIter(steps_per_call=K)``; see docs/performance.md).
* ``zero='auto'|'on'|'off'|'3'`` (``MXNET_ZERO``) — ZeRO-style sharded
  weight update (arXiv 2004.13336): gradients reduce-scatter over the
  data axis, optimizer state and the update live on the local 1/N flat
  tile, fresh params all-gather — ~1/N optimizer-state memory and
  update FLOPs per replica (see ``parallel/zero.py`` and
  docs/performance.md).  ``auto`` engages on a ≥2-device data axis with
  replicated params; composes with the DDP grad overlap (the bucketed
  psum becomes a bucketed psum_scatter), ``steps_per_call``, health
  guards, the dynamic loss scaler, and AOT ``compile()``.  ``'3'``
  (ZeRO-3) additionally keeps the PARAMS at rest as those 1/N tiles:
  forward gathers them layer-bucket by layer-bucket
  (``MXNET_ZERO_GATHER_BUCKET_MB``), backward re-gathers via remat, the
  update writes tiles, and the trailing full all-gather disappears —
  per-replica param residency ~1/N, live full params O(max bucket).
  Callers feed at-rest params from ``init_state`` or
  ``pack_params(...)`` (Module does this itself).
* ``health=StepHealth(...)`` — run-health sentinel: the step
  additionally returns a global gradient norm, an all-params non-finite
  flag, and (with a :class:`~mxnet_tpu.health.DynamicLossScaler`) the
  scaler state — all computed on-device and fused into the program; a
  non-finite step keeps the old params bit-exactly via ``jnp.where``
  (see docs/health_monitoring.md).  ``__call__`` keeps the 4-tuple
  return; the stats land on ``self.last_health`` as device refs.
"""
from __future__ import annotations

from .base import MXNetError
from .compile_cache import signature_of as _signature_of

__all__ = ["compile_train_step", "TrainStep"]


def _loss_from_outputs(outs):
    """Seed the backward exactly like Executor.backward with ones head
    grads: sum of outputs (loss heads carry custom vjp that ignores the
    cotangent's value)."""
    total = None
    for o in outs:
        s = o.astype("float32").sum()
        total = s if total is None else total + s
    return total


def _buffer_key(x):
    """Identity of the underlying device buffer (best effort)."""
    try:
        return ("ptr", x.unsafe_buffer_pointer())
    except Exception:
        return ("id", id(x))


def _place(tree, shardings):
    """Place ``tree`` per ``shardings`` (one sharding broadcast over the
    tree, or a {name: sharding-or-subtree} dict), multiprocess-safe via
    :func:`parallel.zero.put`."""
    import jax

    from .parallel.zero import put

    if shardings is None:
        return tree
    if isinstance(shardings, dict):
        return {n: jax.tree.map(put, tree[n], shardings[n])
                for n in tree}
    return jax.tree.map(lambda x: put(x, shardings), tree)


def _resolve_remat(remat):
    import jax

    if remat is None or remat is False:
        return None
    if remat is True or remat == "full":
        return "full"
    if isinstance(remat, str):
        policy = getattr(jax.checkpoint_policies, remat, None)
        if policy is None:
            raise MXNetError("unknown remat policy %r" % remat)
        return policy
    return remat  # a jax checkpoint policy callable


_Z3_TAG = "zero3_gather"


def _z3_tag(x):
    """Name a gathered full parameter for the ZeRO-3 remat policy."""
    try:
        from jax.ad_checkpoint import checkpoint_name
    except ImportError:  # ancient jax: no names, params stay residuals
        return x
    return checkpoint_name(x, _Z3_TAG)


def _z3_remat_policy():
    """Save every forward residual EXCEPT the tagged gathered params, so
    backward re-issues the bucket all-gathers (deterministic, bit-exact)
    instead of holding O(model) full params alive across the step."""
    import jax

    pol = getattr(jax.checkpoint_policies,
                  "save_anything_except_these_names", None)
    return pol(_Z3_TAG) if pol is not None else None


class TrainStep:
    """Compiled (params, aux, opt_states, batch) -> updated state step."""

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_names=("data",),
                 label_names=("softmax_label",), dtype="float32",
                 batch_sharding_axis="data", compute_dtype=None,
                 remat=None, fixed_param_names=(), param_sharding=None,
                 steps_per_call=1, health=None, zero=None, plan=None):
        import jax
        import jax.numpy as jnp

        from .base import get_env
        from .executor import _trace_fn
        from . import optimizer as opt_mod
        from .compile_cache import ensure_initialized, registry
        from .health import StepHealth

        # first jit owner in the hot path: wire the persistent XLA cache
        # before anything lowers, so this process's compiles are
        # reusable by the next one
        ensure_initialized()
        # composed parallel plan (parallel/plan.py): ONE declaration of
        # the (data, model, pipe, seq) split replacing the per-dimension
        # mesh/param_sharding/zero kwargs.  MXNET_PLAN is the env
        # surface, same "data=4,model=2,zero=3" grammar.
        from .parallel.plan import ParallelPlan

        if plan is None:
            env_plan = get_env("MXNET_PLAN", "", str).strip()
            plan = env_plan or None
        if plan is not None:
            plan = ParallelPlan.parse(plan)
            if plan.pipe > 1:
                raise MXNetError(
                    "plan has a %d-stage pipe axis: pipeline schedules "
                    "run through parallel.pipeline.PipelineTrainStep "
                    "(Module.init_optimizer routes there when given a "
                    "pipe plan)" % plan.pipe)
            if param_sharding not in (None, "replicated"):
                raise MXNetError(
                    "plan=%r owns parameter placement; drop "
                    "param_sharding=%r" % (plan, param_sharding))
            if mesh is None:
                mesh = plan.mesh()
            else:
                plan.validate_mesh(mesh)
            if zero is None:
                zero = plan.zero
        self.plan = plan
        self._plan_tp = plan is not None and plan.model_size(mesh) > 1
        plan_tp = self._plan_tp
        # cached autotune knobs (MXNET_AUTOTUNE=1) arm their env vars
        # BEFORE anything traces — the ops read them at trace time
        from . import autotune as _autotune

        self._autotune_applied = _autotune.apply_train_env(symbol, mesh,
                                                           plan=plan)
        self.symbol = symbol
        self._fwd_fn, self._arg_names, self._aux_names = _trace_fn(
            symbol, is_train=True)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [n for n in self._arg_names
                            if n not in self.data_names
                            and n not in self.label_names]
        self.mesh = mesh

        opt_params = dict(optimizer_params or {})
        fixed = frozenset(fixed_param_names) | frozenset(
            opt_params.pop("fixed_param_names", ()))
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **opt_params)
        elif isinstance(optimizer, opt_mod.Optimizer):
            if opt_params:
                raise MXNetError(
                    "optimizer_params must not be set when passing an "
                    "Optimizer instance (got %r); configure the instance "
                    "instead" % sorted(opt_params))
        else:
            raise MXNetError("optimizer must be a name or Optimizer")
        if not optimizer.supports_fused:
            raise MXNetError("optimizer %s has no fused form"
                             % type(optimizer).__name__)
        self.optimizer = optimizer
        self.lr = optimizer.lr

        # static per-parameter multipliers, resolved by name exactly like
        # Optimizer._get_lr/_get_wd
        lr_mults = {n: optimizer.lr_mult.get(n, 1.0)
                    for n in self.param_names}
        wd_mults = {n: optimizer.wd_mult.get(n, 1.0)
                    for n in self.param_names}
        base_wd = optimizer.wd

        fwd_fn = self._fwd_fn
        remat_policy = _resolve_remat(remat)
        if remat_policy == "full":
            fwd_fn = jax.checkpoint(fwd_fn)
        elif remat_policy is not None:
            fwd_fn = jax.checkpoint(fwd_fn, policy=remat_policy)
        cdtype = compute_dtype
        self._compute_dtype = compute_dtype
        frozen = fixed

        if health is not None and not isinstance(health, StepHealth):
            raise MXNetError("health must be a StepHealth (got %r)"
                             % (health,))
        self._health = health
        self._hstate = None
        self.last_health = None
        scaler = health.scaler if health is not None else None
        # scaler semantics REQUIRE the skip: an overflowed step must not
        # reach the weights, whatever skip_nonfinite says
        skip_on_bad = health is not None and (
            health.skip_nonfinite or scaler is not None)
        from . import quantize as _quantize

        # fp8 training compute (MXNET_FP8): per-site amax histories ride
        # the carried hstate exactly like the dynamic loss scaler, so an
        # armed fp8 build uses the 8-arg/6-output step form even when no
        # StepHealth is configured.  Site count is discovered lazily
        # (first compile/call) from an abstract forward trace.
        fp8_on = _quantize.fp8_enabled()
        self._fp8 = fp8_on
        self._fp8_sites = None
        use_hstate = health is not None or fp8_on
        self._use_hstate = use_hstate
        clip_gnorm = optimizer.clip_global_norm
        rescale = optimizer.rescale_grad

        # compute/collective overlap: under a pure data-parallel mesh
        # the gradient reduction runs as explicit bucketed all-reduces
        # (shard_map) issued in reverse production order so they hide
        # under backward compute; the latency-hiding scheduler flags
        # arm here (best effort — first TrainStep in the process, before
        # the backend initializes)
        from .parallel import overlap as _overlap
        from .parallel import zero as _zero

        _overlap.arm_latency_hiding()
        # decline warnings scope to THIS step: a rebuilt TrainStep with a
        # different config re-reports its own decline reasons
        self._overlap_warner = warner = _overlap.DeclineWarner()
        if plan_tp:
            # composed TP plan: gradient reduction belongs to GSPMD —
            # per-group psum_scatter over the data axis for tiled grads,
            # the model-axis all-reduce where the TP math needs it.  The
            # explicit shard_map DDP path cannot express the joint
            # (model, data) layout, so it stands down without a decline
            # warning (this is the designed path, not a fallback).
            ddp_ax = None
        else:
            ddp_ax = _overlap.ddp_axis(mesh, batch_sharding_axis,
                                       param_sharding, warner=warner,
                                       param_names=self.param_names)
        ddp_bucket = _overlap.grad_bucket_bytes()
        # reverse graph-construction order approximates the order
        # backward produces gradients in
        ddp_order = tuple(reversed(self.param_names))
        self.grad_overlap_axis = ddp_ax

        # ZeRO sharded update (arXiv 2004.13336): optimizer state and the
        # weight update tile 1/N over the data axis — gradients arrive
        # reduce-scattered, the update runs on the local flat tile, fresh
        # params all-gather for the next forward.  Stage 3 keeps the
        # params themselves at rest as those flat tiles and gathers them
        # bucket-by-bucket on demand inside forward (re-gathered by the
        # rematerialized backward), with no trailing full all-gather.
        zmode = _zero.zero_mode(zero)
        zax = _zero.zero_axis(mesh, batch_sharding_axis, param_sharding,
                              mode=zmode, warn=warner.warn,
                              param_names=self.param_names)
        self.zero_axis = zax
        zero_n = int(mesh.shape[zax]) if zax is not None else 0
        zero_min = _zero.min_param_bytes()
        self._zero_n = zero_n
        self._zero_min_bytes = zero_min
        self._frozen = frozen
        self.zero3 = z3_mode = zax is not None and zmode == "3"
        # the tiling layout, cached from CANONICAL shapes the first time
        # it is computed (init_state / compile / pack_params): under
        # ZeRO-3 the live params are flat tiles, so recomputing from
        # them would mis-tile — every later caller reads the cache
        self._zero_lay = None
        z3_bucket = _zero.gather_bucket_bytes()
        if z3_mode and ddp_ax is None and not plan_tp \
                and _overlap.overlap_mode() != "off":
            warner.warn(
                "zero3-gather",
                "zero=3: the bucketed gather prefetch needs the explicit "
                "DDP path (pure data-parallel mesh, MXNET_GRAD_OVERLAP); "
                "params stay sharded at rest with GSPMD-scheduled "
                "gathers instead")
        # set by Module when it drives this step, so the bounded sharded-
        # update dispatch can attach the kvstore's peer diagnosis
        self._kvstore = None

        def cast_compute(x):
            return x.astype(cdtype) if jnp.issubdtype(
                x.dtype, jnp.floating) else x

        def core_step(params, aux, states, batch, rng, lr, t, hstate):
            # delayed scaling: realize this step's per-site (x, w) scales
            # from the carried amax history before the forward traces
            fp8_scales = None
            if fp8_on and "fp8_hist" in hstate:
                fp8_scales = _quantize.fp8_realize_scales(
                    hstate["fp8_hist"])

            def loss_fn(p, b, r):
                args = dict(p)
                args.update(b)
                a = aux
                if cdtype is not None:
                    args = {k: cast_compute(v) for k, v in args.items()}
                    a = {k: cast_compute(v) for k, v in aux.items()}
                if fp8_scales is not None:
                    with _quantize.fp8_trace(fp8_scales) as tr:
                        outs, new_aux = fwd_fn(args, a, r)
                    amax = jnp.stack(tr.amax) if tr.amax else None
                else:
                    outs, new_aux = fwd_fn(args, a, r)
                    amax = None
                if cdtype is not None:
                    new_aux = {k: v.astype(aux[k].dtype)
                               for k, v in new_aux.items()}
                if amax is not None:
                    # fresh amaxes leave the grad transform as an aux
                    # output under a reserved key (a Python-side record
                    # would leak tracers); popped right after the vag
                    new_aux = dict(new_aux)
                    new_aux["__fp8_amax__"] = amax
                loss = _loss_from_outputs(outs)
                if scaler is not None:
                    # scale the loss BEFORE the backward: gradients come
                    # back scaled out of the underflow-prone range
                    loss = loss * hstate["loss_scale"]
                return loss, (outs, new_aux)

            # ZeRO tiling decision: the canonical-shape layout, cached
            # (under ZeRO-3 the traced params are flat at-rest tiles, so
            # recomputing here from live shapes would mis-tile)
            zlay = self.zero_layout(params) if zax is not None else None
            z3 = z3_mode and zlay is not None
            if z3:
                # ZeRO-3 on-demand gather: layer buckets in FORWARD
                # (graph-construction) order, one schedulable collective
                # per bucket, issued back-to-back ahead of the compute
                # that consumes them.  Each gathered full param is
                # tagged; the remat policy below refuses to save tagged
                # values as residuals, so backward re-issues the bucket
                # gathers in reverse order as it needs them — live full
                # params stay O(max bucket), not O(model).
                z3_names = [p for p in self.param_names
                            if zlay[p].sharded]
                z3_sizes = {p: zlay[p].padded * zlay[p].dtype.itemsize
                            for p in z3_names}
                z3_buckets = (_overlap.bucket_partition(
                    z3_names, z3_sizes, z3_bucket) if z3_names else [])
                base_loss_fn = loss_fn

                def z3_loss_fn(p, b, r):
                    full = dict(p)
                    for bucket in z3_buckets:
                        gathered = _zero.gather_bucket(
                            [p[q] for q in bucket],
                            [zlay[q] for q in bucket], mesh, zax)
                        for q, fp in zip(bucket, gathered):
                            full[q] = _z3_tag(fp)
                    return base_loss_fn(full, b, r)

                policy = _z3_remat_policy()
                loss_fn = (jax.checkpoint(z3_loss_fn, policy=policy)
                           if policy is not None else z3_loss_fn)
            vag = None
            if ddp_ax is not None:
                # None = this trace can't run the DDP path (indivisible
                # batch, non-batch-leading outputs); GSPMD fallback below
                vag = _overlap.ddp_value_and_grad(
                    loss_fn, params, batch, rng, mesh, ddp_ax,
                    frozen=frozen, order=ddp_order,
                    bucket_bytes=ddp_bucket, warner=warner,
                    zero_layout=zlay if ddp_ax == zax else None,
                    zero_rest=z3)
            if vag is None:
                vag = jax.value_and_grad(
                    lambda p: loss_fn(p, batch, rng),
                    has_aux=True)(params)
            (loss, (outs, new_aux)), grads = vag
            fp8_amax = None
            if fp8_scales is not None:
                new_aux = dict(new_aux)
                fp8_amax = new_aux.pop("__fp8_amax__", None)
            if zlay is not None:
                # normalize: sharded grads still at full shape came from
                # the GSPMD fallback (or a declined DDP trace) — the
                # sharding constraint on the flat form IS the
                # reduce-scatter (DDP-path grads arrive already flat).
                # ZeRO-3 grads are born flat everywhere (the gather's
                # transpose reduce-scatters); pin their tile layout so
                # the GSPMD fallback lands them scattered, not summed
                # full-size first.
                grads = dict(grads)
                for k, ent in zlay.items():
                    if not ent.sharded or k not in grads:
                        continue
                    if tuple(grads[k].shape) == ent.shape:
                        grads[k] = _zero.shard_flat(grads[k], ent, mesh,
                                                    zax)
                    elif z3 and tuple(grads[k].shape) == (ent.padded,):
                        grads[k] = jax.lax.with_sharding_constraint(
                            grads[k], _zero.flat_sharding(mesh, zax, ent))
            live = [k for k in sorted(grads) if k not in frozen]
            if scaler is not None:
                inv = 1.0 / hstate["loss_scale"]
                loss = loss * inv
                grads = dict(grads)
                for k in live:
                    grads[k] = grads[k] * inv.astype(grads[k].dtype)
            # health sentinel: one extra reduction per parameter, fused
            # into compute that already reads every gradient.  A single
            # NaN/Inf anywhere poisons the sum of squares, so the
            # norm's finiteness doubles as the all-params flag.
            gnorm = opt_mod.global_grad_norm(
                [grads[k] for k in live], rescale)
            nonfinite = ~(jnp.isfinite(loss) & jnp.isfinite(gnorm))
            if clip_gnorm is not None:
                factor = opt_mod.global_norm_scale(gnorm, clip_gnorm)
                grads = dict(grads)
                for k in live:
                    grads[k] = grads[k] * factor.astype(grads[k].dtype)
            def run_updates(_):
                new_params, new_states = {}, {}
                for i, k in enumerate(sorted(grads)):
                    g = grads[k]
                    if k in frozen:
                        new_params[k] = params[k]
                        new_states[k] = states[k]
                        continue
                    if zlay is not None and zlay[k].sharded:
                        # stage 1 slices the replicated weight down to
                        # its tile and gathers the fresh param back;
                        # stage 3 runs on the at-rest tile directly and
                        # returns it still tiled — the next forward's
                        # bucket gather replaces the trailing all-gather
                        driver = (opt_mod.sharded_fused_update_at_rest
                                  if z3 else opt_mod.sharded_fused_update)
                        new_params[k], new_states[k] = driver(
                            optimizer, params[k], g, states[k],
                            lr * lr_mults[k], base_wd * wd_mults[k],
                            t, jax.random.fold_in(rng, i + 1),
                            mesh, zax, zlay[k])
                        continue
                    new_params[k], new_states[k] = optimizer.fused_update(
                        params[k], g, states[k],
                        lr * lr_mults[k], base_wd * wd_mults[k], t,
                        jax.random.fold_in(rng, i + 1))
                return new_params, new_states, new_aux

            if skip_on_bad:
                # the skip happens IN-PROGRAM: a conditional keeps the
                # old buffers bit-exactly, so a poisoned batch is
                # consumed with a zero update and async dispatch never
                # stalls.  lax.cond (not jnp.where): the clean path
                # executes only the update branch, so the sentinel adds
                # no parameter-sized select pass to healthy steps.
                new_params, new_states, new_aux = jax.lax.cond(
                    nonfinite,
                    lambda _: (params, states, aux),
                    run_updates, None)
            else:
                new_params, new_states, new_aux = run_updates(None)
            if scaler is not None:
                good = jnp.where(nonfinite, 0,
                                 hstate["good_steps"] + 1)
                grow = good >= scaler.growth_interval
                scale = jnp.where(
                    nonfinite,
                    jnp.maximum(hstate["loss_scale"] * scaler.backoff,
                                scaler.min_scale),
                    jnp.where(
                        grow,
                        jnp.minimum(hstate["loss_scale"] * scaler.growth,
                                    scaler.max_scale),
                        hstate["loss_scale"]))
                new_hstate = {
                    "loss_scale": scale.astype("float32"),
                    "good_steps": jnp.where(grow, 0, good).astype("int32"),
                }
            else:
                new_hstate = hstate
            if fp8_amax is not None:
                # roll the amax history forward even on skipped steps —
                # but a nonfinite forward amax must not poison it
                safe = jnp.where(jnp.isfinite(fp8_amax), fp8_amax, 0.0)
                new_hstate = dict(new_hstate)
                new_hstate["fp8_hist"] = _quantize.fp8_update_hist(
                    hstate["fp8_hist"], safe)
            stats = {"loss": loss.astype("float32"), "grad_norm": gnorm,
                     "nonfinite": nonfinite}
            if scaler is not None:
                stats["loss_scale"] = hstate["loss_scale"]
            # all outputs come back (multi-loss symbols run fused too);
            # a batch-sharded prefix sharding covers the whole tuple
            return new_params, new_aux, new_states, outs, new_hstate, stats

        if use_hstate:
            step = core_step
        else:
            # legacy 7-arg / 4-output form: the discarded loss value,
            # norm, and flag trace dead and XLA DCEs them — the compiled
            # clean path is unchanged (clip_global_norm, if set, is live
            # through the grads and survives)
            def step(params, aux, states, batch, rng, lr, t):
                p, a, s, outs, _, _ = core_step(
                    params, aux, states, batch, rng, lr, t, {})
                return p, a, s, outs

        K = int(steps_per_call)
        if K < 1:
            raise MXNetError("steps_per_call must be >= 1, got %d" % K)
        self._steps_per_call = K
        if K > 1:
            # multi-step dispatch: one device call scans K donated
            # updates over a (K, batch, …) super-batch — Python dispatch
            # and launch overhead amortize K-fold (the win for small
            # models where per-step host work rivals device time).  lr is
            # held constant across the K inner steps (the scheduler is
            # consulted once per call); t advances per inner step so
            # bias-corrected optimizers stay exact; the per-call rng is
            # folded with the inner step index so dropout masks differ
            # per step.  Outputs come back stacked (K, batch, …); the
            # health stats likewise carry one (K,) entry per inner step.
            base_step = step

            if use_hstate:
                def step(params, aux, states, batch, rng, lr, t, hstate):
                    def body(carry, xs):
                        p, a, s, tk, h = carry
                        bk, k = xs
                        p, a, s, outs, h, stats = base_step(
                            p, a, s, bk, jax.random.fold_in(rng, k), lr,
                            tk, h)
                        return (p, a, s, tk + 1, h), (outs, stats)

                    (params, aux, states, _, hstate), (outs, stats) = \
                        jax.lax.scan(body,
                                     (params, aux, states, t, hstate),
                                     (batch, jnp.arange(K)))
                    return params, aux, states, outs, hstate, stats
            else:
                def step(params, aux, states, batch, rng, lr, t):
                    def body(carry, xs):
                        p, a, s, tk = carry
                        bk, k = xs
                        p, a, s, outs = base_step(
                            p, a, s, bk, jax.random.fold_in(rng, k), lr,
                            tk)
                        return (p, a, s, tk + 1), outs

                    (params, aux, states, _), outs = jax.lax.scan(
                        body, (params, aux, states, t),
                        (batch, jnp.arange(K)))
                    return params, aux, states, outs

        self._step_fn = step
        self._batch_sharding_axis = batch_sharding_axis
        self._param_sharding = param_sharding
        if param_sharding not in (None, "replicated"):
            if mesh is None:
                raise MXNetError(
                    "param_sharding=%r needs a mesh (pass mesh=... or run "
                    "under a dist kvstore)" % (param_sharding,))
            if isinstance(param_sharding, str):
                # validate the style NOW: a typo must fail at construction
                # (inside Module's fused-fallback handling), not on the
                # first training batch
                from .parallel.sharding import param_sharding_rules

                param_sharding_rules(param_sharding)
        # AOT compile() works everywhere except shape-dependent
        # param_sharding (fsdp resolves against concrete shapes)
        self._aot_capable = not (
            mesh is not None and param_sharding not in (None, "replicated"))
        if mesh is not None and param_sharding not in (None, "replicated"):
            # FSDP's largest-dim rule needs concrete parameter SHAPES, so
            # the jitted step is built lazily on the first call
            self._jit_step = None
        elif zax is not None or plan_tp:
            # ZeRO state shardings resolve against the optimizer-state
            # pytree structure — lazily from the first call's concrete
            # states, or from compile()'s abstract ones.  A zero-off TP
            # plan likewise resolves its per-parameter specs against
            # concrete shapes (the divisibility fallback needs them).
            self._jit_step = None
        elif mesh is not None:
            self._jit_step = self._build_jit()
        else:
            self._jit_step = jax.jit(step, donate_argnums=(0, 1, 2))
        self._t = 0
        # recompile guardrail: one guard per symbol name, shared across
        # rebuilt instances so a per-batch reconstruction storm is
        # visible as one counter
        self._recompile_guard = registry.guard(
            "TrainStep(%s)" % (getattr(symbol, "name", None) or "graph"))
        # AOT state (compile()): the ready executable, its input
        # signature, and the recorded stats
        self._aot = None
        self._aot_sig = None
        self.compile_stats = None

    def _build_jit(self, pshard=None, sshard=None):
        """jit the step with parameter/state shardings resolved.

        ``pshard``: {name: NamedSharding} (or None → replicate all);
        ``sshard``: a pytree prefix for the optimizer states (or None).
        Gradients need no annotation: GSPMD propagates shardings and
        inserts the collectives (all-gather for fsdp params,
        all-reduce/reduce-scatter for grads — the TPU form of the
        reference's push/pull).
        """
        import jax

        from .parallel.sharding import (batch_axes, named_sharding,
                                        replicated)

        mesh = self.mesh
        repl = replicated(mesh)
        # batch sharding mirrors shard_batch exactly (data axis plus
        # fsdp when present); pure SP/EP/pipe meshes carry no batch
        # axis, so the batch stays replicated and the mesh axes are
        # consumed inside the ops (ring attention, MoE all_to_all)
        baxes = batch_axes(mesh, self._batch_sharding_axis)
        # a packed super-batch carries an unsharded leading K axis; the
        # batch dim (and the stacked outputs' step dim) sits behind it
        lead = [None] if self._steps_per_call > 1 else []
        bshard = named_sharding(mesh, *(lead + [baxes])) if baxes else repl
        if pshard is None:
            pshard = repl
        if sshard is None:
            sshard = repl if not isinstance(pshard, dict) else pshard
        bdict = {n: bshard for n in self.data_names + self.label_names}
        # __call__ re-places host inputs onto these when the mesh spans
        # processes (jit cannot auto-commit to non-addressable devices)
        self._in_bshard = bdict
        self._in_repl = repl
        in_sh = (pshard, repl, sshard, bdict, repl, None, None)
        out_sh = (pshard, repl, sshard, bshard)
        if self._use_hstate:
            # + scaler/fp8 state in, + new state / health stats out —
            # scalars and small histories, replicated everywhere
            in_sh = in_sh + (repl,)
            out_sh = out_sh + (repl, repl)
        return jax.jit(self._step_fn, in_shardings=in_sh,
                       out_shardings=out_sh, donate_argnums=(0, 1, 2))

    def _build_sharded_jit(self, params, states):
        """Resolve param_sharding rules against concrete shapes and jit.

        Optimizer state leaves follow their parameter's sharding when
        shaped like the weight (momentum/adam moments), else replicate
        (scalars, schedules) — the ZeRO contract that sharded params
        carry sharded optimizer states.
        """
        import jax

        from .parallel.sharding import (apply_rules, param_sharding_rules,
                                        replicated)

        if self._plan_tp and self._param_sharding in (None, "replicated"):
            pshard = self.plan.param_shardings(self.mesh, params)
        else:
            rules = self._param_sharding
            if isinstance(rules, str):
                rules = param_sharding_rules(rules)
            pshard = apply_rules(self.mesh, params, rules)
        repl = replicated(self.mesh)
        sshard = {
            n: jax.tree.map(
                lambda leaf, _n=n: pshard[_n]
                if tuple(leaf.shape) == tuple(params[_n].shape) else repl,
                states[n])
            for n in states
        }
        self._in_pshard = pshard
        self._in_sshard = sshard
        return self._build_jit(pshard, sshard)

    def _build_zero_jit(self, params, states):
        """jit with the ZeRO state layout resolved: flat ``(padded,)``
        state leaves tile over the data axis (group-locally
        ``P((model, data))`` for a composed plan's TP entries), scalars
        and unsharded params' states replicate.  Stage 1 keeps the
        params at their canonical placement (replicated, or the plan's
        TP specs — the all-gather lives inside the program); stage 3
        pins the at-rest flat params to their tile sharding in AND out —
        fresh tiles leave the step still sharded.  Under a plan, a
        parameter too small for tiling stays at its canonical TP
        sharding, weight-shaped state leaves included."""
        import jax

        from .parallel import zero as _zero
        from .parallel.sharding import named_sharding, replicated

        mesh = self.mesh
        zax = self.zero_axis
        lay = self.zero_layout(params)
        repl = replicated(mesh)
        canon = None
        if self._plan_tp:
            canon = {n: named_sharding(
                        mesh, *self.plan.param_spec(n, lay[n].shape, mesh))
                     for n in lay}

        def state_shard(n):
            if canon is not None and not lay[n].sharded:
                # canonical TP placement: moments follow the weight
                return jax.tree.map(
                    lambda leaf, _n=n: canon[_n]
                    if tuple(getattr(leaf, "shape", ())) == lay[_n].shape
                    else repl, states[n])
            return _zero.state_sharding(states[n], lay[n], mesh, zax)

        sshard = {n: state_shard(n) for n in states}
        pshard = None
        if self.zero3:
            pshard = {n: (_zero.flat_sharding(mesh, zax, lay[n])
                          if lay[n].sharded
                          else (canon[n] if canon is not None else repl))
                      for n in params}
        elif canon is not None:
            pshard = dict(canon)
        self._in_pshard = (pshard if pshard is not None
                           else replicated(self.mesh))
        self._in_sshard = sshard
        return self._build_jit(pshard, sshard)

    def _spans_processes(self):
        """True when the step's mesh holds devices this process cannot
        address (a multi-controller pod run)."""
        cached = getattr(self, "_spans_cache", None)
        if cached is None:
            import jax

            mesh = self.mesh
            cached = self._spans_cache = bool(
                mesh is not None
                and any(d.process_index != jax.process_index()
                        for d in mesh.devices.flat))
        return cached

    def zero_layout(self, params):
        """{name: ZeroParam} tiling decision for this step, or None when
        the sharded update is off/declined.  Deterministic in parameter
        shapes/dtypes (works on ShapeDtypeStructs too).  Cached on first
        computation — which must see CANONICAL shapes (``init_state``,
        ``compile``, ``pack_params`` all qualify), because under ZeRO-3
        the live params are flat tiles the tiling cannot be derived
        from."""
        if self.zero_axis is None:
            return None
        if self._zero_lay is not None:
            return self._zero_lay
        from .parallel import zero as _zero

        if self._plan_tp:
            # composed plan: TP params get group-local shard-major
            # tiles, everything else the classic data-axis tiling
            self._zero_lay = _zero.plan_layout(
                params, self.mesh, self.zero_axis,
                self.plan.param_specs(params, self.mesh),
                min_bytes=self._zero_min_bytes, frozen=self._frozen)
        else:
            self._zero_lay = _zero.layout(params, self._zero_n,
                                          self._zero_min_bytes,
                                          self._frozen)
        return self._zero_lay

    def pack_params(self, params):
        """Canonical full params -> this step's at-rest layout: under
        ZeRO-3 sharded entries become flat 1/N tiles placed ``P(axis)``
        (bit-exact round trip — padding is zeros); identity otherwise.
        Module calls this before the first fused step; direct ZeRO-3
        callers must feed ``__call__`` packed params (``init_state``
        already returns them packed)."""
        lay = self.zero_layout(params)
        if not self.zero3 or lay is None:
            return params
        from .parallel import zero as _zero

        return _zero.pack_params(params, lay, self.mesh, self.zero_axis)

    def unpack_params(self, params):
        """At-rest params -> canonical host numpy dict (identity unless
        ZeRO-3).  Requires the tiles to be addressable."""
        lay = self._zero_lay
        if not self.zero3 or lay is None:
            return params
        from .parallel import zero as _zero

        return _zero.unpack_params(params, lay)

    def memory_report(self, params=None, states=None):
        """Bench accounting, labeled per column: ``opt_state_bytes`` and
        ``params_bytes_per_replica`` are what ONE replica holds at rest
        (read from the live arrays' shardings — full-model params under
        zero=off/stage-1, ~1/N tiles under ZeRO-3), and their sum is
        ``total_state_bytes_per_replica`` — params included, so the
        stage-1-vs-3 A/B compares like with like.
        ``update_gather_bytes`` is the stage-1 trailing fresh-param
        all-gather (0 under ZeRO-3 — there is none);
        ``gather_bytes_per_step`` is the per-step param-gather traffic
        whichever stage moves it (stage 1: the trailing gather; ZeRO-3:
        forward bucket gathers + the backward re-gather).  AOT
        ``memory_analysis`` numbers ride along when compiled."""
        from .parallel import zero as _zero

        out = {"zero": self.zero_axis is not None, "zero3": self.zero3}
        if states is not None:
            out["opt_state_bytes"] = _zero.state_bytes_per_replica(states)
        if params is not None:
            out["params_bytes_per_replica"] = \
                _zero.params_bytes_per_replica(params)
            if states is not None:
                out["total_state_bytes_per_replica"] = (
                    out["opt_state_bytes"]
                    + out["params_bytes_per_replica"])
        lay = self._zero_lay
        if lay is None and params is not None:
            lay = self.zero_layout(params)
        if lay is None:
            out["update_gather_bytes"] = 0
            out["gather_bytes_per_step"] = 0
        elif self.zero3:
            out["update_gather_bytes"] = 0
            out["gather_bytes_per_step"] = _zero.zero3_gather_bytes(lay)
        else:
            out["update_gather_bytes"] = _zero.update_gather_bytes(lay)
            out["gather_bytes_per_step"] = out["update_gather_bytes"]
        if self._aot is not None:
            try:
                mem = self._aot.memory_analysis()
                out["aot_argument_bytes"] = int(
                    mem.argument_size_in_bytes)
                out["aot_temp_bytes"] = int(mem.temp_size_in_bytes)
            except Exception:
                pass
        return out

    def _abstract_inputs(self, shapes, dtype="float32"):
        """Abstract (params, aux, states, batch, rng, lr, t[, hstate])
        matching what ``__call__`` dispatches for per-step ``shapes``:
        parameter/aux avals from the shape-inference pass, optimizer
        states via ``eval_shape``, the super-batch leading K axis when
        ``steps_per_call > 1``, a concrete rng key, the python-float lr
        (weak type, exactly like the live call), and the int32 step."""
        import jax
        import jax.numpy as jnp

        from .symbol.symbol import _infer_param_shapes

        shapes = {k: tuple(v) for k, v in dict(shapes).items()}
        all_shapes = _infer_param_shapes(self.symbol, dict(shapes))
        S = jax.ShapeDtypeStruct
        params = {n: S(tuple(all_shapes[n]), jnp.dtype(dtype))
                  for n in self.param_names}
        aux = {n: S(tuple(all_shapes[n]), jnp.dtype("float32"))
               for n in self._aux_names}
        lay = self.zero_layout(params)
        states = {}
        for n in self.param_names:
            w = params[n]
            if lay is not None and lay[n].sharded:
                # ZeRO layout: every weight-shaped leaf is born flat
                w = S((lay[n].padded,), jnp.dtype(dtype))
            states[n] = jax.eval_shape(self.optimizer.init_fused_state, w)
        if self.zero3 and lay is not None:
            # ZeRO-3: the step's param arguments are the at-rest tiles
            params = {n: (S((lay[n].padded,), jnp.dtype(dtype))
                          if lay[n].sharded else params[n])
                      for n in params}
        K = self._steps_per_call
        batch = {}
        for n in self.data_names + self.label_names:
            if n not in shapes:
                raise MXNetError("compile(shapes) is missing a shape "
                                 "for input %r" % n)
            shp = ((K,) + shapes[n]) if K > 1 else shapes[n]
            batch[n] = S(shp, jnp.dtype("float32"))
        args = (params, aux, states, batch, jax.random.PRNGKey(0),
                float(self.lr), jnp.asarray(1, "int32"))
        if self._use_hstate:
            self._fp8_site_count(params, aux, batch)
            args = args + (self._init_hstate(),)
        return args

    def compile(self, shapes, dtype="float32"):
        """AOT warmup: lower and compile the step for ``shapes`` NOW.

        ``shapes`` maps each data/label name to its per-step shape (the
        same dict ``init_state`` takes); the leading ``steps_per_call``
        axis is added internally.  The resulting executable is kept and
        used directly by ``__call__`` whenever the live inputs match the
        compiled signature, so the first training step pays zero
        compile; a mismatch falls back to the lazily-jitted path (which
        still hits the persistent cache).  Compile wall time, FLOPs, and
        executable size are recorded as a profiler compile event and
        returned (also kept on ``self.compile_stats``)."""
        import time

        from . import profiler
        from .compile_cache import cache_stats

        if self._jit_step is None and not self._aot_capable:
            raise MXNetError(
                "AOT compile is unavailable with shape-dependent "
                "param_sharding=%r: the sharded jit resolves against "
                "concrete parameters on the first call"
                % (self._param_sharding,))
        args = self._abstract_inputs(shapes, dtype=dtype)
        if self._jit_step is None:
            if self.zero_axis is not None:
                # ZeRO: the abstract states carry the flat layout, which
                # is all the sharding resolution needs
                self._jit_step = self._build_zero_jit(args[0], args[2])
            else:
                # zero-off TP plan: specs resolve from abstract shapes
                self._jit_step = self._build_sharded_jit(args[0], args[2])
        hits_before = cache_stats()["hits"]
        t0 = time.perf_counter()
        lowered = self._jit_step.lower(*args)
        lower_s = time.perf_counter() - t0
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        flops = None
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, list) else ca
            flops = float(ca.get("flops", 0.0)) or None
        except Exception:
            pass
        exe_bytes = None
        try:
            mem = compiled.memory_analysis()
            exe_bytes = int(getattr(mem, "generated_code_size_in_bytes",
                                    0)) or None
        except Exception:
            pass
        cache_hit = cache_stats()["hits"] > hits_before
        sig = _signature_of(*args)
        self._aot = compiled
        self._aot_sig = sig
        # seed the guard so the first matching live call is not counted
        # as a second trace
        self._recompile_guard.observe(sig)
        self.compile_stats = profiler.compile_event(
            self._recompile_guard.name, compile_s, flops=flops,
            executable_bytes=exe_bytes, cache_hit=cache_hit,
            lower_s=round(lower_s, 6), aot=True)
        return self.compile_stats

    def __call__(self, params, aux, states, batch, rng, lr=None, t=None):
        import jax
        import jax.numpy as jnp

        K = self._steps_per_call
        if t is None:
            self._t += K
            t = self._t - K + 1  # first inner step's post-increment count
        else:
            self._t = int(t) + K - 1
        # Two input hygiene passes before the donated call:
        # 1. commit uncommitted arrays (jnp.zeros products) so the jit
        #    signature is identical on every step — no recompiles;
        # 2. donated pytrees must not alias each other (some optimizers
        #    seed state from the weight buffer; XLA may also alias
        #    identical outputs) — copy duplicates.
        seen = set()

        def dedupe(x):
            if not getattr(x, "committed", True):
                x = jax.device_put(x, next(iter(x.devices())))
            k = _buffer_key(x)
            if k in seen:
                return jnp.copy(x)
            seen.add(k)
            return x

        params, aux, states = jax.tree.map(
            dedupe, (params, aux, states))
        if self._jit_step is None:
            if self.zero_axis is not None:
                self._jit_step = self._build_zero_jit(params, states)
            else:
                self._jit_step = self._build_sharded_jit(params, states)
        if getattr(self, "_in_pshard", None) is not None:
            # committed single-device arrays cannot be auto-resharded to
            # a non-trivial layout by jit; place them explicitly (no-op
            # once the donated outputs carry the sharding)
            params = _place(params, self._in_pshard)
            states = _place(states, self._in_sshard)
        lr = self.lr if lr is None else lr
        t = jnp.asarray(t, "int32")
        if self._spans_processes():
            # pod run: EVERY array argument must be a global jax.Array —
            # jit cannot place host batches/rng/scalars across processes
            # itself.  The host batch is read as the GLOBAL batch (each
            # rank materializes its own rows), matching the
            # single-process semantics bit for bit.
            repl = self._in_repl
            aux = _place(aux, repl)
            batch = _place(dict(batch), self._in_bshard)
            rng = _place(rng, repl)
            lr = _place(jnp.asarray(lr, "float32"), repl)
            t = _place(t, repl)
            if self._use_hstate and self._hstate is None:
                self._fp8_site_count(params, aux, batch)
                self._hstate = self._init_hstate()
            if self._hstate is not None:
                self._hstate = _place(self._hstate, repl)
        if not self._use_hstate:
            call_args = (params, aux, states, batch, rng, lr, t)
        else:
            if self._hstate is None:
                self._fp8_site_count(params, aux, batch)
                self._hstate = self._init_hstate()
            call_args = (params, aux, states, batch, rng, lr, t,
                         self._hstate)
        sig = _signature_of(*call_args)
        self._recompile_guard.observe(sig)

        def dispatch():
            out = None
            if self._aot is not None and sig == self._aot_sig:
                try:
                    out = self._aot(*call_args)
                except Exception:
                    # Compiled executables validate avals/shardings before
                    # running (donation has not happened yet), so falling
                    # back to the lazy jit is safe; drop the AOT
                    # executable for good rather than re-failing every
                    # step.
                    self._aot = None
                    out = None
            if out is None:
                out = self._jit_step(*call_args)
            return out

        if self.zero_axis is not None:
            from .parallel import zero as _zero
            from .testing import faults

            def dispatch_zero():
                # host-side boundaries of the in-program collectives:
                # before dispatch = the gradient reduce-scatter (and,
                # under ZeRO-3, the forward bucket all-gathers), after
                # the result = the stage-1 fresh-param all-gather
                faults.inject("zero_update")
                if self.zero3:
                    faults.inject("zero_gather")
                res = dispatch()
                faults.inject("zero_update")
                return res

            what = None
            active = None
            if self.zero3 and faults.active("zero_gather"):
                active = True
                what = ("ZeRO-3 bucketed parameter all-gather (forward "
                        "bucket gathers + backward re-gather)")
            out = _zero.bounded_dispatch(dispatch_zero,
                                         kvstore=self._kvstore,
                                         active=active, what=what)
        else:
            out = dispatch()
        if not self._use_hstate:
            return out
        (params, aux, states, outs, self._hstate,
         self.last_health) = out
        return params, aux, states, outs

    def _init_hstate(self):
        import jax.numpy as jnp

        scaler = self._health.scaler if self._health is not None else None
        h = {}
        if scaler is not None:
            h["loss_scale"] = jnp.asarray(scaler.init_scale, "float32")
            h["good_steps"] = jnp.asarray(0, "int32")
        if self._fp8 and self._fp8_sites:
            from . import quantize as _quantize

            h["fp8_hist"] = _quantize.fp8_hist_init(self._fp8_sites)
        return h

    def export_hstate(self):
        """Host snapshot of the carried step health state — the dynamic
        loss scale, its good-step streak, and the fp8 delayed-scaling
        amax history — or None when this step carries none.  The capture
        side of the in-memory plan migration (``parallel/elastic.py``);
        checkpoint-free, bit-exact."""
        import numpy as np

        if self._hstate is None:
            return None
        return {k: np.asarray(v) for k, v in self._hstate.items()}

    def load_hstate(self, hstate):
        """Install a captured :meth:`export_hstate` snapshot onto THIS
        step (the reshard side of the in-memory migration, or a restore
        without a disk round trip).  Dtypes are pinned to the carried
        contract (f32 scale/history, i32 streak) so the jit signature
        matches a fresh :meth:`_init_hstate`; an fp8 history also pins
        the site count, which is topology-independent."""
        import numpy as np

        import jax.numpy as jnp

        if hstate is None:
            return
        if not self._use_hstate:
            raise MXNetError(
                "cannot install a migrated hstate: this TrainStep "
                "carries no health state (no loss scaler and fp8 off) — "
                "the new plan's step must be armed like the old one")
        h = {}
        if "loss_scale" in hstate:
            h["loss_scale"] = jnp.asarray(float(hstate["loss_scale"]),
                                          "float32")
            h["good_steps"] = jnp.asarray(
                int(hstate.get("good_steps", 0)), "int32")
        if "fp8_hist" in hstate:
            hist = np.asarray(hstate["fp8_hist"])
            h["fp8_hist"] = jnp.asarray(hist, "float32")
            self._fp8_sites = int(hist.shape[0])
        self._hstate = h or None

    def _fp8_site_count(self, params, aux, batch):
        """Count the fp8 matmul sites one forward claims (once, via an
        abstract trace) — the leading dim of the carried amax history.

        Works from avals only, so live arrays and ShapeDtypeStructs both
        serve.  Under ZeRO-3 the live params are flat at-rest tiles; the
        cached layout recovers their canonical shapes.  The super-batch
        leading K axis is stripped when ``steps_per_call > 1``."""
        if not self._fp8 or self._fp8_sites is not None:
            return self._fp8_sites
        import jax
        import jax.numpy as jnp

        from . import quantize as _quantize

        S = jax.ShapeDtypeStruct
        lay = self._zero_lay if self.zero3 else None
        cparams = {}
        for n, v in dict(params).items():
            shp, dt = tuple(v.shape), v.dtype
            if lay is not None and n in lay and lay[n].sharded:
                shp, dt = tuple(lay[n].shape), lay[n].dtype
            cparams[n] = S(shp, jnp.dtype(dt))
        K = self._steps_per_call
        abatch = {n: S(tuple(v.shape)[1:] if K > 1 else tuple(v.shape),
                       jnp.dtype(v.dtype))
                  for n, v in dict(batch).items()}
        aaux = {n: S(tuple(v.shape), jnp.dtype(v.dtype))
                for n, v in dict(aux).items()}
        fwd = self._fwd_fn
        rng = jax.random.PRNGKey(0)

        def probe(p, a, b):
            args = dict(p)
            args.update(b)
            return fwd(args, a, rng)

        with _quantize.fp8_trace() as tr:
            jax.eval_shape(probe, cparams, aaux, abatch)
        self._fp8_sites = len(tr.names)
        return self._fp8_sites

    @property
    def loss_scale(self):
        """Current dynamic loss scale as a float (host sync), or None
        when no scaler is configured."""
        if self._hstate is None or "loss_scale" not in self._hstate:
            return None
        return float(self._hstate["loss_scale"])

    def init_state(self, shapes, dtype="float32", seed=0):
        """Allocate params/aux/optimizer-states as raw jax arrays via the
        shape inference pass + Xavier-ish scaling (bench/profiling
        convenience; real training initializes through Module)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .symbol.symbol import _infer_param_shapes

        all_shapes = _infer_param_shapes(self.symbol, dict(shapes))
        key = jax.random.PRNGKey(seed)
        params, aux, states = {}, {}, {}
        for n in self.param_names:
            shp = all_shapes[n]
            key, sub = jax.random.split(key)
            if n.endswith(("_gamma",)):
                params[n] = jnp.ones(shp, dtype)
            elif n.endswith(("_bias", "_beta")):
                params[n] = jnp.zeros(shp, dtype)
            else:
                fan_in = int(np.prod(shp[1:])) if len(shp) > 1 else shp[0]
                scale = (2.0 / max(1, fan_in)) ** 0.5
                params[n] = scale * jax.random.normal(sub, shp, dtype)
        lay = self.zero_layout(params)
        if lay is not None:
            from .parallel import zero as _zero
        for n in self.param_names:
            if lay is not None and lay[n].sharded:
                states[n] = _zero.init_state(
                    self.optimizer, params[n], lay[n], self.mesh,
                    self.zero_axis)
            else:
                states[n] = self.optimizer.init_fused_state(params[n])
        for n in self._aux_names:
            shp = all_shapes[n]
            aux[n] = jnp.ones(shp, "float32") if n.endswith("_var") \
                else jnp.zeros(shp, "float32")
        if lay is not None and self.zero3:
            # ZeRO-3: hand back the params already at rest (flat 1/N
            # tiles), matching what __call__ expects and returns
            params = _zero.pack_params(params, lay, self.mesh,
                                       self.zero_axis)
        return params, aux, states


def compile_train_step(symbol, **kwargs):
    return TrainStep(symbol, **kwargs)
