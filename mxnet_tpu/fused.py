"""Fused train step — the TPU performance path.

The reference's fastest path pushes per-node cached engine ops plus
separate optimizer-update ops (SURVEY.md §3.1).  On TPU the whole thing —
forward, backward, optimizer update, and (under a mesh) the gradient
all-reduce — compiles into ONE XLA program with donated parameter buffers:
zero host round-trips per step, maximal fusion, collectives overlapped
with backward compute by XLA's scheduler.  This is what `Module` uses when
`fit` runs with a compiled step, and what bench.py measures.
"""
from __future__ import annotations

import functools

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["compile_train_step", "TrainStep"]


def _loss_from_outputs(outs):
    """Seed the backward exactly like Executor.backward with ones head
    grads: sum of outputs (loss heads carry custom vjp that ignores the
    cotangent's value)."""
    total = None
    for o in outs:
        s = o.sum()
        total = s if total is None else total + s
    return total


class TrainStep:
    """Compiled (params, aux, opt_state, batch) -> updated state step."""

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_names=("data",),
                 label_names=("softmax_label",), dtype="float32",
                 batch_sharding_axis="data"):
        import jax

        from .executor import _trace_fn

        self.symbol = symbol
        self._fwd_fn, self._arg_names, self._aux_names = _trace_fn(
            symbol, is_train=True)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [n for n in self._arg_names
                            if n not in self.data_names
                            and n not in self.label_names]
        self.mesh = mesh
        opt_params = dict(optimizer_params or {})
        self.lr = float(opt_params.get("learning_rate", 0.01))
        self.momentum = float(opt_params.get("momentum", 0.0))
        self.wd = float(opt_params.get("wd", 0.0))
        self.rescale = float(opt_params.get("rescale_grad", 1.0))
        if optimizer not in ("sgd",):
            raise MXNetError("TrainStep currently compiles sgd; use Module "
                             "update path for %r" % optimizer)

        fwd_fn = self._fwd_fn
        data_names, label_names = self.data_names, self.label_names
        lr, momentum, wd, rescale = (self.lr, self.momentum, self.wd,
                                     self.rescale)

        frozen = frozenset(opt_params.get("fixed_param_names", ()))

        def step(params, aux, moms, batch, rng, lr):
            def loss_fn(p):
                args = dict(p)
                args.update(batch)
                outs, new_aux = fwd_fn(args, aux, rng)
                return _loss_from_outputs(outs), (outs, new_aux)

            grads, (outs, new_aux) = jax.grad(
                loss_fn, has_aux=True)(params)
            new_params, new_moms = {}, {}
            for k, g in grads.items():
                if k in frozen:
                    new_params[k] = params[k]
                    new_moms[k] = moms[k]
                    continue
                g = g * rescale
                if momentum:
                    m = momentum * moms[k] - lr * (g + wd * params[k])
                    new_params[k] = params[k] + m
                    new_moms[k] = m
                else:
                    new_params[k] = params[k] - lr * (g + wd * params[k])
                    new_moms[k] = moms[k]
            return new_params, new_aux, new_moms, outs[0]

        if mesh is not None:
            from .parallel.sharding import named_sharding, replicated

            repl = replicated(mesh)
            bshard = named_sharding(mesh, batch_sharding_axis)
            self._jit_step = jax.jit(
                step,
                in_shardings=(repl, repl, repl,
                              {n: bshard for n in
                               data_names + label_names}, repl, None),
                out_shardings=(repl, repl, repl, bshard),
                donate_argnums=(0, 1, 2))
        else:
            self._jit_step = jax.jit(step, donate_argnums=(0, 1, 2))

    def __call__(self, params, aux, moms, batch, rng, lr=None):
        return self._jit_step(params, aux, moms, batch, rng,
                              self.lr if lr is None else lr)

    def init_state(self, shapes, dtype="float32", seed=0):
        """Allocate params/aux/momentum as raw jax arrays via the shape
        inference pass + Xavier-ish scaling (bench/profiling convenience;
        real training initializes through Module)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .symbol.symbol import _infer_param_shapes

        all_shapes = _infer_param_shapes(self.symbol, dict(shapes))
        key = jax.random.PRNGKey(seed)
        params, aux, moms = {}, {}, {}
        for n in self.param_names:
            shp = all_shapes[n]
            key, sub = jax.random.split(key)
            if n.endswith(("_gamma",)):
                params[n] = jnp.ones(shp, dtype)
            elif n.endswith(("_bias", "_beta")):
                params[n] = jnp.zeros(shp, dtype)
            else:
                fan_in = int(np.prod(shp[1:])) if len(shp) > 1 else shp[0]
                scale = (2.0 / max(1, fan_in)) ** 0.5
                params[n] = scale * jax.random.normal(sub, shp, dtype)
            moms[n] = jnp.zeros(shp, dtype)
        for n in self._aux_names:
            shp = all_shapes[n]
            aux[n] = jnp.ones(shp, "float32") if n.endswith("_var") \
                else jnp.zeros(shp, "float32")
        return params, aux, moms


def compile_train_step(symbol, **kwargs):
    return TrainStep(symbol, **kwargs)
