"""Gluon — the imperative high-level API (reference ``python/mxnet/gluon/``,
new in the 0.11 reference)."""
from . import parameter
from .parameter import Parameter, ParameterDict
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .trainer import Trainer
from . import utils
from . import data
from . import rnn

__all__ = ["Parameter", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "nn",
           "loss", "Trainer", "utils", "data", "rnn"]
