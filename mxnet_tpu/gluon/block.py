"""Gluon Block / HybridBlock / SymbolBlock.

Reference: ``python/mxnet/gluon/block.py`` — ``Block`` (imperative
container with scoped parameters), ``HybridBlock`` (``hybridize()`` caches
the graph: reference builds a ``CachedOp``, ``block.py:361``).

TPU-native: ``hybridize()`` jit-compiles ``hybrid_forward`` over
(params, inputs) — the CachedOp replay loop collapses into one XLA
program, which on TPU is exactly what you want (SURVEY.md §7 item 6).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

from ..base import MXNetError
from .. import autograd
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock"]

_name_counter = threading.local()


def _auto_prefix(cls_name):
    counts = getattr(_name_counter, "counts", None)
    if counts is None:
        counts = _name_counter.counts = {}
    base = re.sub("(?!^)([A-Z]+)", r"_\1", cls_name).lower()
    idx = counts.get(base, 0)
    counts[base] = idx + 1
    return "%s%d_" % (base, idx)


class _BlockScope:
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _auto_prefix(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, shared=None)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        _BlockScope._current.value = self._old_scope


class Block:
    """Base container (reference ``gluon.Block``)."""

    def __init__(self, prefix=None, params=None):
        hint = re.sub("(?!^)([A-Z]+)", r"_\1",
                      self.__class__.__name__).lower()
        self._prefix, self._params = _BlockScope.create(prefix, params, hint)
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=repr(block).replace("\n", "\n  "))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All parameters of self and children (reference
        ``collect_params``; ``select`` is a regex filter)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def save_params(self, fname):
        self.collect_params().save(fname, strip_prefix=self.prefix)

    def load_params(self, fname, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(fname, ctx, allow_missing, ignore_extra,
                                   restore_prefix=self.prefix)

    def hybridize(self, active=True):
        for child in self._children.values():
            child.hybridize(active)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for param in self.params.values():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


class HybridBlock(Block):
    """Block whose ``hybrid_forward`` can compile to one XLA program.

    Imperative mode runs ``hybrid_forward(nd, x, **params)`` through the
    normal op registry.  After ``hybridize()``, the whole composite —
    all children included — executes as a single jitted function of
    (param buffers, input buffers); gradients flow through the jitted
    program via the autograd tape's registered-op mechanism by treating
    the cached program as one fused op.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fn = None
        self._param_order = None

    def hybridize(self, active=True):
        self._active = active
        self._cached_fn = None
        super().hybridize(active)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    def infer_shape(self, *args):
        self._infer_params(args)

    def _infer_params(self, args):
        """Resolve deferred parameter shapes by abstract evaluation."""
        for x in args:
            if isinstance(x, NDArray):
                self.shape_inference_hook(x)
        # default: let forward fail and tell user; subclasses (nn layers)
        # override _shape_from_input

    def shape_inference_hook(self, x):
        pass

    def __call__(self, *args):
        try:
            return self.forward(*args)
        except DeferredInitializationError:
            # deferred init: infer shapes from inputs then retry (the
            # reference defers to the first forward, block.py `_build_cache`)
            self._resolve_deferred(args)
            return self.forward(*args)

    def _resolve_deferred(self, args):
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child._resolve_deferred(args)
        for name, param in self.params.items():
            if param._deferred_init is not None and param.shape is not None \
                    and all(s != 0 for s in param.shape):
                param._shape_from_data(param.shape)

    def forward(self, x, *args):
        from .. import ndarray as ndm

        if self._active and autograd.is_recording():
            # jitting under the tape: run imperatively (ops already cached
            # per-op); full-program fusion applies in inference mode
            pass
        if self._active and not autograd.is_recording():
            return self._call_cached(x, *args)
        params = {k: v.data() for k, v in self.params.items()}
        kwargs = {self._short_name(k): v for k, v in params.items()}
        return self.hybrid_forward(ndm, x, *args, **kwargs)

    def _short_name(self, full):
        return full[len(self.prefix):] if full.startswith(self.prefix) \
            else full

    def _call_cached(self, *args):
        import jax

        from .. import ndarray as ndm

        if self._cached_fn is None:
            names = list(self.params.keys())

            def fn(param_bufs, in_bufs):
                param_nds = {self._short_name(n): NDArray(b)
                             for n, b in zip(names, param_bufs)}
                in_nds = [NDArray(b) for b in in_bufs]
                out = self.hybrid_forward(ndm, *in_nds, **param_nds)
                if isinstance(out, (list, tuple)):
                    return tuple(o._data for o in out)
                return out._data

            self._cached_fn = jax.jit(fn)
            self._param_order = names
        param_bufs = tuple(self.params[n].data()._data
                           for n in self._param_order)
        in_bufs = tuple(a._data if isinstance(a, NDArray) else a
                        for a in args)
        out = self._cached_fn(param_bufs, in_bufs)
        if isinstance(out, tuple):
            return [NDArray(o) for o in out]
        return NDArray(out)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(Block):
    """Wrap a Symbol graph as a Block (reference ``gluon.SymbolBlock``):
    symbolic checkpoints become Gluon layers.

    The graph replays through the imperative op path node by node, so it
    records on the autograd tape — training with ``Trainer`` works like
    any other Block.  Auxiliary states (BatchNorm moving stats) update in
    place via the ops' ``mutable_inputs`` contract.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol.symbol import Group, Symbol

        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        from ..base import MXNetError

        for s in inputs:
            node, _ = s._outputs[0]
            if not node.is_variable:
                raise MXNetError(
                    "SymbolBlock inputs must be Variables; %r is an op "
                    "output — slice the graph so its inputs are "
                    "Variables (sym.get_internals()) before wrapping"
                    % s.name)
        self._symbol = outputs
        self._input_names = [s.name for s in inputs]
        aux_names = set(outputs.list_auxiliary_states())
        # label variables of loss heads are not parameters: when not
        # listed as inputs they are fed zeros at forward (loss heads
        # ignore labels outside training; reference users slice the head
        # off with get_internals — this keeps full checkpoints loadable)
        self._label_names = [
            n for n in outputs.list_arguments()
            if n.endswith("_label") and n not in self._input_names]
        for name in outputs.list_arguments() + list(aux_names):
            if name in self._input_names or name in self._label_names:
                continue
            self.params.get(
                name, allow_deferred_init=True,
                grad_req="null" if name in aux_names else "write")

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load ``prefix-symbol.json`` (+ params file) into a block
        (reference ``SymbolBlock.imports``)."""
        from .. import symbol as sym_mod
        from ..ndarray import load as nd_load
        from ..symbol.symbol import Variable

        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        block = SymbolBlock(sym, [Variable(n) for n in input_names])
        if param_file:
            loaded = nd_load(param_file)
            for k, v in loaded.items():
                name = k.split(":", 1)[1] if ":" in k else k
                if name in block.params:
                    block.params[name].set_data(v)
        return block

    def forward(self, *args):
        from ..base import MXNetError
        from ..ndarray.ndarray import imperative_invoke

        if len(args) != len(self._input_names):
            raise MXNetError("SymbolBlock expects %d inputs (%s), got %d"
                             % (len(self._input_names),
                                self._input_names, len(args)))
        feeds = dict(zip(self._input_names, args))
        # shape inference fills deferred parameter shapes AND the label
        # placeholder shapes from the input shapes
        from ..symbol.symbol import _infer_param_shapes

        shapes = _infer_param_shapes(
            self._symbol, {n: tuple(a.shape) for n, a in feeds.items()})
        for p in self.params.values():
            if p._data is None:
                if p.name in shapes:
                    p._shape_from_data(tuple(shapes[p.name]))
                else:
                    raise MXNetError(
                        "cannot infer shape for parameter %r" % p.name)
        if self._label_names:
            from .. import autograd as _ag

            if _ag.is_recording():
                # zero-fed labels would yield gradients against
                # fabricated targets — refuse instead of training wrong
                raise MXNetError(
                    "SymbolBlock holds loss-head label inputs %s: slice "
                    "the head off (sym.get_internals()) or list them as "
                    "inputs before training" % self._label_names)

        env = {}
        from ..ndarray import zeros as nd_zeros

        batch = args[0].shape[0] if args else 1
        for node in self._symbol._topo():
            if node.is_variable:
                if node.name in feeds:
                    env[(id(node), 0)] = feeds[node.name]
                elif node.name in self._label_names:
                    env[(id(node), 0)] = nd_zeros(
                        tuple(shapes.get(node.name, (batch,))))
                else:
                    env[(id(node), 0)] = self.params[node.name].data()
                continue
            ins = [env[(id(src), i)] for (src, i) in node.inputs]
            outs = imperative_invoke(node.op.name, ins, dict(node.attrs))
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
        results = [env[(id(n), i)] for (n, i) in self._symbol._outputs]
        return results[0] if len(results) == 1 else results
