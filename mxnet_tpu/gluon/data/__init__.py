"""Gluon data API (reference ``python/mxnet/gluon/data/``)."""
from .dataset import Dataset, ArrayDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import DataLoader
from . import vision

__all__ = ["Dataset", "ArrayDataset", "RecordFileDataset", "Sampler",
           "SequentialSampler", "RandomSampler", "BatchSampler",
           "DataLoader", "vision"]
