"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``)."""
from __future__ import annotations

import numpy as np

from ...ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be "
                "specified if batch_sampler is")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __iter__(self):
        for batch in self._batch_sampler:
            yield self._batchify_fn([self._dataset[i] for i in batch])

    def __len__(self):
        return len(self._batch_sampler)
