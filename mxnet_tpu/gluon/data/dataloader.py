"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``)."""
from __future__ import annotations

import queue
import threading

import numpy as np

from ...ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return array(data)


class _Stop:
    pass


class _Raised:
    def __init__(self, exc):
        self.exc = exc


class _DevicePrefetchingIter:
    """Background-thread device staging over a batch generator: batches
    are ``device_put`` and *readied* on the worker (``block_until_ready``
    runs here), so the training loop's ``next()`` hands back an array
    whose h2d transfer already happened while the previous step ran."""

    def __init__(self, source, depth, device):
        import jax

        self._jax = jax
        self._device = device if device is not None else \
            jax.local_devices()[0]
        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, args=(iter(source),), daemon=True)
        self._thread.start()

    def _stage(self, item):
        jax = self._jax
        if isinstance(item, NDArray):
            out = NDArray(jax.device_put(item._data, self._device),
                          item.context)
            jax.block_until_ready(out._data)
            return out
        if isinstance(item, (list, tuple)):
            return type(item)(self._stage(x) for x in item)
        return item

    def _worker(self, it):
        try:
            for batch in it:
                if self._stop.is_set():
                    return
                self._put(self._stage(batch))
        except Exception as exc:  # propagate to the consumer thread
            self._put(_Raised(exc))
        finally:
            self._put(_Stop)

    def _put(self, item):
        """Bounded put that a close() can always unblock: retry until
        the queue has room or the stop flag is raised (close() drains,
        so a worker wedged on a full queue gets out either way)."""
        while True:
            try:
                self._queue.put(item, timeout=0.05)
                return
            except queue.Full:
                if self._stop.is_set():
                    return

    def close(self, timeout=5):
        """Stop the staging worker deterministically (the PR 2/9
        teardown contract): raise the stop flag, drain the queue so a
        blocked put exits, and join with ``timeout``."""
        self._stop.set()
        self._closed = True
        t = self._thread
        while t is not None and t.is_alive():
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)
            timeout -= 0.05
            if timeout <= 0:
                break
        self._thread = None

    def __del__(self):
        try:
            self.close(timeout=0.2)
        except Exception:  # mxlint: disable=MX008 — interpreter teardown
            pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        item = self._queue.get()
        if item is _Stop:
            raise StopIteration
        if isinstance(item, _Raised):
            raise item.exc
        return item


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, prefetch=0, device=None,
                 seed=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset), seed=seed) \
                    if shuffle else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be "
                "specified if batch_sampler is")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        # prefetch=N overlaps batchify + h2d transfer of the next N
        # batches with the current step (gluon analogue of wrapping a
        # DataIter in io.DevicePrefetchIter); device defaults to the
        # first local jax device
        self._prefetch = int(prefetch)
        self._device = device

    def _batches(self):
        for batch in self._batch_sampler:
            yield self._batchify_fn([self._dataset[i] for i in batch])

    def __iter__(self):
        if self._prefetch > 0:
            return _DevicePrefetchingIter(self._batches(), self._prefetch,
                                          self._device)
        return self._batches()

    def __len__(self):
        return len(self._batch_sampler)
