"""Datasets (reference ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray import NDArray, array

__all__ = ["Dataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]
        return self.transform(first, lazy)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of arrays/lists (reference ``ArrayDataset``)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, \
                "All arrays must have the same length"
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference ``RecordFileDataset``)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO

        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
