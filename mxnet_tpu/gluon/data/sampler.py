"""Samplers (reference ``python/mxnet/gluon/data/sampler.py``)."""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Shuffled indices from a per-instance seeded stream: the order is
    a pure function of (seed, epoch), never of global-RNG call order."""

    def __init__(self, length, seed=0):
        self._length = length
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        return iter(self._rng.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Groups a sampler into batches; last_batch in keep/discard/rollover
    (reference ``BatchSampler``)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of keep, discard, or rollover")

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // self._batch_size
        raise ValueError(
            "last_batch must be one of keep, discard, or rollover")
