"""Vision datasets (reference ``python/mxnet/gluon/data/vision.py``:
MNIST, FashionMNIST, CIFAR10, ImageRecordDataset).

No network egress in this build: the datasets read canonical files from
``root`` if present and raise a clear error otherwise; ``SyntheticDataset``
provides deterministic stand-in data for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...base import MXNetError
from ...ndarray import array
from .dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "SyntheticDataset"]


class SyntheticDataset(ArrayDataset):
    """Deterministic synthetic image dataset for tests/benches."""

    def __init__(self, num_samples=1000, shape=(1, 28, 28), num_classes=10,
                 seed=0):
        rng = np.random.RandomState(seed)
        data = rng.rand(num_samples, *shape).astype("float32")
        label = rng.randint(0, num_classes, num_samples).astype("int32")
        super().__init__(data, label)


class _IdxDataset(Dataset):
    def __init__(self, root, image_file, label_file, train):
        self._root = os.path.expanduser(root)
        img_path = os.path.join(self._root, image_file)
        lbl_path = os.path.join(self._root, label_file)
        if not (_exists(img_path) and _exists(lbl_path)):
            raise MXNetError(
                "Dataset files not found under %s (no network in this "
                "environment; place %s and %s there, or use "
                "SyntheticDataset)" % (root, image_file, label_file))
        self._data = _read_idx(img_path).astype("float32") / 255.0
        self._data = self._data.reshape(self._data.shape[0],
                                        self._data.shape[1],
                                        self._data.shape[2], 1)
        self._label = _read_idx(lbl_path).astype("int32")

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


def _exists(p):
    return os.path.exists(p) or os.path.exists(p + ".gz")


def _read_idx(path):
    opener = open
    if not os.path.exists(path):
        path, opener = path + ".gz", gzip.open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


class MNIST(_IdxDataset):
    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        image = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
        label = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"
        super().__init__(root, image, label, train)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(Dataset):
    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        root = os.path.expanduser(root)
        files = ["data_batch_%d.bin" % i for i in range(1, 6)] if train \
            else ["test_batch.bin"]
        paths = [os.path.join(root, f) for f in files]
        if not all(os.path.exists(p) for p in paths):
            raise MXNetError(
                "CIFAR10 binary batches not found under %s (no network in "
                "this environment; use SyntheticDataset)" % root)
        blobs = [np.frombuffer(open(p, "rb").read(), dtype=np.uint8)
                 .reshape(-1, 3073) for p in paths]
        blob = np.concatenate(blobs, axis=0)
        self._label = blob[:, 0].astype("int32")
        self._data = blob[:, 1:].reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1).astype("float32") / 255.0

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)
