"""Gluon losses (reference ``python/mxnet/gluon/loss.py``)."""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SoftmaxCrossEntropyLoss",
           "SigmoidBinaryCrossEntropyLoss", "KLDivLoss", "HuberLoss",
           "HingeLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        loss = nd.square(pred - label.reshape(pred.shape))
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        loss = nd.abs(pred - label.reshape(pred.shape))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class SoftmaxCrossEntropyLoss(Loss):
    """Reference ``SoftmaxCrossEntropyLoss``: sparse_label selects
    pick-style NLL; axis is the class axis."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -nd.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            loss = -nd.sum(pred * label, axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)), the stable form
            loss = nd.relu(pred) - pred * label + \
                nd.Activation(-nd.abs(pred), act_type="softrelu")
        else:
            loss = -(nd.log(pred + 1e-12) * label +
                     nd.log(1. - pred + 1e-12) * (1. - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        loss = label * (nd.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        loss = nd.abs(pred - label.reshape(pred.shape))
        loss = nd.where(loss > self._rho,
                        loss - 0.5 * self._rho,
                        (0.5 / self._rho) * nd.square(loss))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        from .. import ndarray as nd

        loss = nd.relu(self._margin - pred * label.reshape(pred.shape))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)
