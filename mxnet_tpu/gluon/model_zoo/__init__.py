"""Gluon model zoo (reference ``python/mxnet/gluon/model_zoo/``)."""
from . import vision
from .vision import get_model
