"""Gluon model zoo — vision networks.

Reference: ``python/mxnet/gluon/model_zoo/vision/`` (resnet/vgg/alexnet/
squeezenet/densenet/mobilenet generators; SURVEY.md §2.2).  Same
constructor surface and block structure, built from this framework's
HybridBlocks so every model hybridizes into one compiled program.

``pretrained=True`` is not available (no model store in the build
environment) and raises.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["get_model", "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "get_resnet",
           "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg", "alexnet", "squeezenet1_0",
           "squeezenet1_1", "densenet121", "densenet161", "densenet169",
           "densenet201", "mobilenet1_0", "mobilenet0_75", "mobilenet0_5",
           "mobilenet0_25", "get_mobilenet", "MobileNet", "AlexNet",
           "ResNetV1", "ResNetV2", "VGG", "SqueezeNet", "DenseNet"]


def _no_pretrained(pretrained):
    if pretrained:
        raise MXNetError("pretrained weights are not available in this "
                         "build (no model store); initialize and train")


# ---------------------------------------------------------------------------
# ResNet (reference resnet.py: BasicBlockV1/V2, BottleneckV1/V2)
# ---------------------------------------------------------------------------

def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x2 = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x2 + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1,
                                strides=stride, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x2 = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x2 + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


_RESNET_SPEC = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
                34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
                50: ("bottle_neck", [3, 4, 6, 3],
                     [64, 256, 512, 1024, 2048]),
                101: ("bottle_neck", [3, 4, 23, 3],
                      [64, 256, 512, 1024, 2048]),
                152: ("bottle_neck", [3, 8, 36, 3],
                      [64, 256, 512, 1024, 2048])}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        in_ch = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride, in_ch))
            in_ch = channels[i + 1]
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=channels[-1])

    @staticmethod
    def _make_layer(block, layers, channels, stride, in_channels):
        layer = nn.HybridSequential(prefix="")
        layer.add(block(channels, stride,
                        downsample=(channels != in_channels or
                                    stride != 1),
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, downsample=False,
                            in_channels=channels))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.Flatten(x))


class ResNetV2(ResNetV1):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        HybridBlock.__init__(self, **kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(nn.BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        in_ch = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride, in_ch))
            in_ch = channels[i + 1]
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=channels[-1])


_V1_BLOCKS = {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1}
_V2_BLOCKS = {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}


def get_resnet(version, num_layers, pretrained=False, classes=1000,
               **kwargs):
    _no_pretrained(pretrained)
    if num_layers not in _RESNET_SPEC:
        raise MXNetError("no resnet spec for %d layers" % num_layers)
    block_name, layers, channels = _RESNET_SPEC[num_layers]
    if version == 1:
        return ResNetV1(_V1_BLOCKS[block_name], layers, channels,
                        classes=classes, **kwargs)
    if version == 2:
        return ResNetV2(_V2_BLOCKS[block_name], layers, channels,
                        classes=classes, **kwargs)
    raise MXNetError("resnet version must be 1 or 2")


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)


# ---------------------------------------------------------------------------
# VGG (reference vgg.py)
# ---------------------------------------------------------------------------

_VGG_SPEC = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        for num, ch in zip(layers, filters):
            for _ in range(num):
                self.features.add(nn.Conv2D(ch, kernel_size=3, padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    layers, filters = _VGG_SPEC[num_layers]
    return VGG(layers, filters, **kwargs)


def vgg11(**kw): return get_vgg(11, **kw)
def vgg13(**kw): return get_vgg(13, **kw)
def vgg16(**kw): return get_vgg(16, **kw)
def vgg19(**kw): return get_vgg(19, **kw)
def vgg11_bn(**kw): return get_vgg(11, batch_norm=True, **kw)
def vgg13_bn(**kw): return get_vgg(13, batch_norm=True, **kw)
def vgg16_bn(**kw): return get_vgg(16, batch_norm=True, **kw)
def vgg19_bn(**kw): return get_vgg(19, batch_norm=True, **kw)


# ---------------------------------------------------------------------------
# AlexNet (reference alexnet.py)
# ---------------------------------------------------------------------------

class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Conv2D(192, 5, padding=2, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Conv2D(384, 3, padding=1, activation="relu"))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


# ---------------------------------------------------------------------------
# SqueezeNet (reference squeezenet.py)
# ---------------------------------------------------------------------------

class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, kernel_size=1,
                                 activation="relu")
        self.expand1 = nn.Conv2D(expand1x1, kernel_size=1,
                                 activation="relu")
        self.expand3 = nn.Conv2D(expand3x3, kernel_size=3, padding=1,
                                 activation="relu")

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.Concat(self.expand1(x), self.expand3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in ("1.0", "1.1"):
            raise MXNetError("squeezenet version must be '1.0' or '1.1'")
        self.features = nn.HybridSequential(prefix="")
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in ((16, 64, 64), (16, 64, 64),
                               (32, 128, 128)):
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in ((32, 128, 128), (48, 192, 192),
                               (48, 192, 192), (64, 256, 256)):
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in ((48, 192, 192), (48, 192, 192),
                               (64, 256, 256), (64, 256, 256)):
                self.features.add(_Fire(sq, e1, e3))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential(prefix="")
        self.output.add(nn.Conv2D(classes, kernel_size=1,
                                  activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# DenseNet (reference densenet.py)
# ---------------------------------------------------------------------------

class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, kernel_size=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, kernel_size=3, padding=1,
                                use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.Concat(x, self.body(x), dim=1)


_DENSENET_SPEC = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                    use_bias=False))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(3, 2, 1))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            blk = nn.HybridSequential(prefix="")
            for _ in range(num_layers):
                blk.add(_DenseLayer(growth_rate, bn_size, dropout))
            self.features.add(blk)
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.Conv2D(num_features // 2,
                                            kernel_size=1,
                                            use_bias=False))
                self.features.add(nn.AvgPool2D(2, 2))
                num_features //= 2
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _densenet(n):
    def make(pretrained=False, **kwargs):
        _no_pretrained(pretrained)
        init, growth, cfg = _DENSENET_SPEC[n]
        return DenseNet(init, growth, cfg, **kwargs)
    make.__name__ = "densenet%d" % n
    return make


densenet121 = _densenet(121)
densenet161 = _densenet(161)
densenet169 = _densenet(169)
densenet201 = _densenet(201)


# ---------------------------------------------------------------------------
# MobileNet v1 (reference mobilenet.py)
# ---------------------------------------------------------------------------

def _add_conv(seq, channels, kernel=1, stride=1, pad=0, num_group=1):
    seq.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu"))


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        ch = int(32 * multiplier)
        _add_conv(self.features, ch, kernel=3, stride=2, pad=1)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 +
                       [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 +
                    [1024] * 2]
        strides = [1, 2, 1, 2, 1, 2] + [1] * 5 + [2, 1]
        for dwc, c, s in zip(dw_channels, channels, strides):
            _add_conv(self.features, dwc, kernel=3, stride=s, pad=1,
                      num_group=dwc)   # depthwise
            _add_conv(self.features, c)  # pointwise
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_mobilenet(multiplier, pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return MobileNet(multiplier, **kwargs)


def mobilenet1_0(**kw): return get_mobilenet(1.0, **kw)
def mobilenet0_75(**kw): return get_mobilenet(0.75, **kw)
def mobilenet0_5(**kw): return get_mobilenet(0.5, **kw)
def mobilenet0_25(**kw): return get_mobilenet(0.25, **kw)


# ---------------------------------------------------------------------------

_MODELS = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
}


def get_model(name, **kwargs):
    """Build a model by name (reference ``model_zoo.vision.get_model``)."""
    name = name.lower()
    if name not in _MODELS:
        raise MXNetError("model %r is not in the zoo (known: %s)"
                         % (name, sorted(_MODELS)))
    return _MODELS[name](**kwargs)


# ---------------------------------------------------------------------------
# Inception V3 (reference inception.py; input 299x299)
# ---------------------------------------------------------------------------

def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for channels, kernel_size, strides, padding in conv_settings:
        out.add(_make_basic_conv(channels=channels,
                                 kernel_size=kernel_size,
                                 strides=strides, padding=padding))
    return out


class _InceptionBlock(HybridBlock):
    """Concat of parallel branches (the A/B/C/D/E blocks share this
    shape; branch settings differ)."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = branches
        for i, b in enumerate(branches):
            self.register_child(b, "b%d" % i)

    def hybrid_forward(self, F, x):
        return F.Concat(*[b(x) for b in self.branches], dim=1)


def _make_A(pool_features):
    return _InceptionBlock([
        _make_branch(None, (64, 1, 1, 0)),
        _make_branch(None, (48, 1, 1, 0), (64, 5, 1, 2)),
        _make_branch(None, (64, 1, 1, 0), (96, 3, 1, 1),
                     (96, 3, 1, 1)),
        _make_branch("avg", (pool_features, 1, 1, 0))])


def _make_B():
    return _InceptionBlock([
        _make_branch(None, (384, 3, 2, 0)),
        _make_branch(None, (64, 1, 1, 0), (96, 3, 1, 1),
                     (96, 3, 2, 0)),
        _make_branch("max")])


def _make_C(channels_7x7):
    return _InceptionBlock([
        _make_branch(None, (192, 1, 1, 0)),
        _make_branch(None, (channels_7x7, 1, 1, 0),
                     (channels_7x7, (1, 7), 1, (0, 3)),
                     (192, (7, 1), 1, (3, 0))),
        _make_branch(None, (channels_7x7, 1, 1, 0),
                     (channels_7x7, (7, 1), 1, (3, 0)),
                     (channels_7x7, (1, 7), 1, (0, 3)),
                     (channels_7x7, (7, 1), 1, (3, 0)),
                     (192, (1, 7), 1, (0, 3))),
        _make_branch("avg", (192, 1, 1, 0))])


def _make_D():
    return _InceptionBlock([
        _make_branch(None, (192, 1, 1, 0), (320, 3, 2, 0)),
        _make_branch(None, (192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
                     (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)),
        _make_branch("max")])


class _InceptionE(HybridBlock):
    """The E block's 3x3 branches split into parallel 1x3/3x1 halves."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.b0 = _make_branch(None, (320, 1, 1, 0))
        self.b1_stem = _make_basic_conv(channels=384, kernel_size=1,
                                        strides=1, padding=0)
        self.b1_a = _make_basic_conv(channels=384, kernel_size=(1, 3),
                                     strides=1, padding=(0, 1))
        self.b1_b = _make_basic_conv(channels=384, kernel_size=(3, 1),
                                     strides=1, padding=(1, 0))
        self.b2_stem = nn.HybridSequential(prefix="")
        self.b2_stem.add(_make_basic_conv(channels=448, kernel_size=1,
                                          strides=1, padding=0))
        self.b2_stem.add(_make_basic_conv(channels=384, kernel_size=3,
                                          strides=1, padding=1))
        self.b2_a = _make_basic_conv(channels=384, kernel_size=(1, 3),
                                     strides=1, padding=(0, 1))
        self.b2_b = _make_basic_conv(channels=384, kernel_size=(3, 1),
                                     strides=1, padding=(1, 0))
        self.b3 = _make_branch("avg", (192, 1, 1, 0))

    def hybrid_forward(self, F, x):
        s1 = self.b1_stem(x)
        s2 = self.b2_stem(x)
        return F.Concat(self.b0(x), self.b1_a(s1), self.b1_b(s1),
                        self.b2_a(s2), self.b2_b(s2), self.b3(x), dim=1)


class Inception3(HybridBlock):
    """Inception V3 (reference ``Inception3``; Szegedy et al. 2015)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=2, padding=0))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=1, padding=0))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                           strides=1, padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1,
                                           strides=1, padding=0))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3,
                                           strides=1, padding=0))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_InceptionE())
        self.features.add(_InceptionE())
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return Inception3(**kwargs)


_MODELS["inceptionv3"] = inception_v3
__all__.append("inception_v3")
