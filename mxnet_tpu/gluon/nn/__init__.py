"""Gluon neural-network layers (reference ``python/mxnet/gluon/nn/``)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *   # noqa: F401,F403

from .basic_layers import __all__ as _basic_all
from .conv_layers import __all__ as _conv_all

__all__ = list(_basic_all) + list(_conv_all)
