"""Gluon basic layers (reference ``python/mxnet/gluon/nn/basic_layers.py``):
Sequential, Dense, Dropout, BatchNorm, LayerNorm, Embedding, Flatten,
Activation, LeakyReLU, Lambda."""
from __future__ import annotations

from ..block import Block, HybridBlock
from ...base import MXNetError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "Embedding", "Flatten", "Activation",
           "LeakyReLU", "Lambda", "HybridLambda", "MultiHeadAttention",
           "MoE",
           "TransformerBlock"]


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class Dense(HybridBlock):
    """Fully connected (reference ``Dense``): deferred in_units."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(units,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None

    def forward(self, x):
        from ... import ndarray as nd

        if self.weight._data is None:
            in_units = x.shape[-1] if not self._flatten else \
                int(_prod(x.shape[1:]))
            self.weight._shape_from_data((self._units, in_units))
        if self.bias is not None and self.bias._data is None:
            self.bias._shape_from_data((self._units,))
        args = [x, self.weight.data()]
        if self.bias is not None:
            args.append(self.bias.data())
        out = nd.FullyConnected(*args, num_hidden=self._units,
                                flatten=self._flatten,
                                no_bias=self.bias is None)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out

    hybrid_forward = None


def _prod(t):
    p = 1
    for v in t:
        p *= v
    return p


class Dropout(HybridBlock):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def forward(self, x):
        from ... import ndarray as nd

        return nd.Dropout(x, p=self._rate)


class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer,
                allow_deferred_init=True, differentiable=False)

    def forward(self, x):
        from ... import ndarray as nd

        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            if p._data is None:
                p._shape_from_data((c,))
        return nd.BatchNorm(x, self.gamma.data(), self.beta.data(),
                            self.running_mean.data(),
                            self.running_var.data(),
                            axis=self._axis, momentum=self._momentum,
                            eps=self._epsilon, fix_gamma=not self._scale,
                            use_global_stats=self._use_global_stats)


class InstanceNorm(HybridBlock):
    def __init__(self, epsilon=1e-5, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init="ones",
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init="zeros",
                                        allow_deferred_init=True)

    def forward(self, x):
        from ... import ndarray as nd

        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._shape_from_data((c,))
        return nd.InstanceNorm(x, self.gamma.data(), self.beta.data(),
                               eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init="ones",
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init="zeros",
                                        allow_deferred_init=True)

    def forward(self, x):
        from ... import ndarray as nd

        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._shape_from_data((c,))
        return nd.LayerNorm(x, self.gamma.data(), self.beta.data(),
                            axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype)

    def forward(self, x):
        from ... import ndarray as nd

        return nd.Embedding(x, self.weight.data(),
                            input_dim=self._input_dim,
                            output_dim=self._output_dim)


class Flatten(HybridBlock):
    def forward(self, x):
        from ... import ndarray as nd

        return nd.Flatten(x)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def forward(self, x):
        from ... import ndarray as nd

        return nd.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        from ... import ndarray as nd

        return nd.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


HybridLambda = Lambda


class MultiHeadAttention(HybridBlock):
    """Causal multi-head self-attention over the fused
    ``MultiHeadAttention`` op (the Gluon face of the transformer family;
    ``seq_parallel=True`` rides ring attention over the mesh's 'seq'
    axis — see ``parallel/sequence.py``)."""

    def __init__(self, num_heads, causal=True, seq_parallel=False,
                 in_units=0, weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self._causal = causal
        self._seq_parallel = seq_parallel
        with self.name_scope():
            self.in_weight = self.params.get(
                "in_weight", shape=(3 * in_units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            self.in_bias = self.params.get(
                "in_bias", shape=(3 * in_units,), init="zeros",
                allow_deferred_init=True)
            self.out_weight = self.params.get(
                "out_weight", shape=(in_units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            self.out_bias = self.params.get(
                "out_bias", shape=(in_units,), init="zeros",
                allow_deferred_init=True)

    def forward(self, x):
        from ... import ndarray as nd

        c = x.shape[-1]
        for p, shp in ((self.in_weight, (3 * c, c)),
                       (self.in_bias, (3 * c,)),
                       (self.out_weight, (c, c)),
                       (self.out_bias, (c,))):
            if p._data is None:
                p._shape_from_data(shp)
        return nd.MultiHeadAttention(
            x, self.in_weight.data(), self.in_bias.data(),
            self.out_weight.data(), self.out_bias.data(),
            num_heads=self._num_heads, causal=self._causal,
            seq_parallel=self._seq_parallel)


class MoE(HybridBlock):
    """Top-k routed mixture-of-experts feed-forward (the Gluon face of
    the ``MoE`` op; routing/dispatch in ``parallel/expert.py``).

    ``forward(x)`` returns ``(out, aux_loss)``: scale ``aux_loss`` (the
    Switch-style load-balancing term, 1.0 at perfect balance) and add it
    to the training objective.  With ``expert_parallel=True`` tokens and
    experts shard over the active mesh's 'expert' axis and the
    dispatch/return hops ride ``all_to_all`` on ICI."""

    def __init__(self, num_experts, hidden_size=0, top_k=2,
                 capacity_factor=1.25, expert_parallel=False, in_units=0,
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._num_experts = num_experts
        self._hidden_size = hidden_size
        self._top_k = top_k
        self._capacity_factor = capacity_factor
        self._expert_parallel = expert_parallel
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(in_units, num_experts),
                init=weight_initializer, allow_deferred_init=True)
            self.w1_weight = self.params.get(
                "w1_weight", shape=(num_experts, in_units, hidden_size),
                init=weight_initializer, allow_deferred_init=True)
            self.w2_weight = self.params.get(
                "w2_weight", shape=(num_experts, hidden_size, in_units),
                init=weight_initializer, allow_deferred_init=True)

    def forward(self, x):
        from ... import ndarray as nd

        d = x.shape[-1]
        h = self._hidden_size or 4 * d
        e = self._num_experts
        for p, shp in ((self.gate_weight, (d, e)),
                       (self.w1_weight, (e, d, h)),
                       (self.w2_weight, (e, h, d))):
            if p._data is None:
                p._shape_from_data(shp)
        out, aux = nd.MoE(
            x, self.gate_weight.data(), self.w1_weight.data(),
            self.w2_weight.data(), num_experts=e, top_k=self._top_k,
            hidden_size=h, capacity_factor=self._capacity_factor,
            expert_parallel=self._expert_parallel)
        return out, aux


class TransformerBlock(HybridBlock):
    """Pre-norm decoder block: x + MHA(LN(x)); x + FFN(LN(x)) with GELU
    (mirrors ``models/transformer.transformer_block`` on the Gluon
    side)."""

    def __init__(self, d_model, num_heads, d_ff=None, seq_parallel=False,
                 **kwargs):
        super().__init__(**kwargs)
        d_ff = d_ff or 4 * d_model
        with self.name_scope():
            self.ln1 = LayerNorm()
            self.attn = MultiHeadAttention(num_heads,
                                           seq_parallel=seq_parallel)
            self.ln2 = LayerNorm()
            self.ffn1 = Dense(d_ff, flatten=False)
            self.ffn2 = Dense(d_model, flatten=False)

    def forward(self, x):
        from ... import ndarray as nd

        h = self.attn(self.ln1(x))
        x = x + h
        h = self.ffn1(self.ln2(x))
        h = nd.Activation(h, act_type="gelu")
        return x + self.ffn2(h)
