"""Gluon conv/pool layers (reference ``python/mxnet/gluon/nn/conv_layers.py``):
Conv1D/2D/3D, Conv2DTranspose, MaxPool/AvgPool/GlobalPool 1-3D."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool2D", "GlobalAvgPool2D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, use_bias, activation, in_channels, ndim,
                 op_name="Convolution", **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._activation = activation
        self._ndim = ndim
        self._op_name = op_name
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels
                          else 0) + self._kernel
            else:  # Deconvolution: (in, out/groups, *k)
                wshape = (in_channels if in_channels else 0,
                          channels // groups) + self._kernel
            self.weight = self.params.get("weight", shape=wshape,
                                          allow_deferred_init=True)
            self.bias = self.params.get("bias", shape=(channels,),
                                        init="zeros",
                                        allow_deferred_init=True) \
                if use_bias else None

    def forward(self, x):
        from ... import ndarray as nd

        in_c = x.shape[1]
        if self.weight._data is None:
            if self._op_name == "Convolution":
                self.weight._shape_from_data(
                    (self._channels, in_c // self._groups) + self._kernel)
            else:
                self.weight._shape_from_data(
                    (in_c, self._channels // self._groups) + self._kernel)
        if self.bias is not None and self.bias._data is None:
            self.bias._shape_from_data((self._channels,))
        args = [x, self.weight.data()]
        if self.bias is not None:
            args.append(self.bias.data())
        fn = getattr(nd, self._op_name)
        out = fn(*args, kernel=self._kernel, stride=self._strides,
                 pad=self._padding, dilate=self._dilation,
                 num_filter=self._channels, num_group=self._groups,
                 no_bias=self.bias is None)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, use_bias=True, activation=None,
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, activation, in_channels, 1,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, use_bias=True,
                 activation=None, in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, activation, in_channels, 2,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 use_bias=True, activation=None, in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, activation, in_channels, 3,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, use_bias=True,
                 activation=None, in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, activation, in_channels, 2,
                         op_name="Deconvolution", **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 ndim, ceil_mode=False, **kwargs):
        super().__init__(**kwargs)
        self._kernel = _tup(pool_size, ndim)
        self._stride = _tup(strides if strides is not None else pool_size,
                            ndim)
        self._pad = _tup(padding, ndim)
        self._global = global_pool
        self._pool_type = pool_type
        self._ceil = ceil_mode

    def forward(self, x):
        from ... import ndarray as nd

        return nd.Pooling(x, kernel=self._kernel, stride=self._stride,
                          pad=self._pad, pool_type=self._pool_type,
                          global_pool=self._global,
                          pooling_convention="full" if self._ceil
                          else "valid")


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", 1,
                         **kwargs)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", 2,
                         **kwargs)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", 3,
                         **kwargs)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", 1,
                         **kwargs)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", 2,
                         **kwargs)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", 3,
                         **kwargs)


class GlobalMaxPool2D(_Pool):
    def __init__(self, **kwargs):
        super().__init__((1, 1), None, 0, True, "max", 2, **kwargs)


class GlobalAvgPool2D(_Pool):
    def __init__(self, **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", 2, **kwargs)
