"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` — deferred initialization,
grad_req, per-context data, ``ParameterDict`` with prefix scoping.
Single-controller SPMD note: one logical buffer per parameter (sharding
over the mesh replaces per-GPU copies)."""
from __future__ import annotations

from collections import OrderedDict

from ..base import MXNetError
from .. import autograd
from ..ndarray import NDArray, zeros
from ..initializer import InitDesc, create as init_create

__all__ = ["Parameter", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter used before shapes were known (reference same name)."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        self._data = None
        self._grad = None
        self._deferred_init = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)

    # -- initialization -------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        from ..initializer import Uniform

        default_init = default_init or Uniform()
        if self._data is not None and not force_reinit:
            return
        if self.shape is None or any(s == 0 for s in self.shape):
            if not self.allow_deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s has unknown shape and deferred init is "
                    "not allowed" % self.name)
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        from ..context import current_context

        ctx = ctx or current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        data = zeros(self.shape, ctx, dtype=self.dtype)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_create(initializer)
        initializer(InitDesc(self.name,
                             {"__init__": ""} if init or self.init else {}),
                    data)
        self._data = data
        if self.grad_req != "null":
            self._grad = zeros(self.shape, ctx, dtype=self.dtype)
            autograd.mark_variables([self._data], [self._grad],
                                    self.grad_req)

    def _shape_from_data(self, data_shape):
        """Resolve deferred shape once input shapes are seen."""
        if self.shape is None:
            self.shape = tuple(data_shape)
        else:
            self.shape = tuple(ds if s == 0 else s
                               for s, ds in zip(self.shape, data_shape))
        if self._deferred_init is not None:
            init, ctx, default_init = self._deferred_init
            self._deferred_init = None
            self._finish_init(init, ctx, default_init)

    # -- access ---------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s not initialized yet: run a forward pass "
                    "first" % self.name)
            raise MXNetError("Parameter %s has not been initialized"
                             % self.name)
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        if self._grad is None:
            raise MXNetError("Parameter %s has no gradient (grad_req=%s)"
                             % (self.name, self.grad_req))
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0.0

    def set_data(self, data):
        if self._data is None:
            self.shape = tuple(data.shape)
            self._data = data.copy() if isinstance(data, NDArray) else data
            if self.grad_req != "null":
                # keep parity with _finish_init: directly-set parameters
                # (SymbolBlock.imports, load_params) are trainable too
                self._grad = zeros(self.shape, dtype=self.dtype)
                autograd.mark_variables([self._data], [self._grad],
                                        self.grad_req)
        else:
            data.copyto(self._data)

    def var(self):
        from ..symbol import Variable

        return Variable(self.name, shape=self.shape, dtype=self.dtype,
                        lr_mult=self.lr_mult, wd_mult=self.wd_mult)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)


class ParameterDict:
    """Prefix-scoped parameter dictionary (reference ``ParameterDict``)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return "ParameterDict %s(%s)" % (
            self._prefix, ", ".join(self._params))

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Get or create a parameter named prefix+name."""
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            for k, v in kwargs.items():
                if v is not None and getattr(param, k, None) in (None, v) \
                        or k == "shape" and param.shape is None:
                    setattr(param, k, tuple(v) if k == "shape" else v)
            return param
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("Cannot update: duplicate parameter %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from ..initializer import Uniform

        for param in self._params.values():
            param.initialize(None, ctx, init or Uniform(),
                             force_reinit=force_reinit)

    def zero_grad(self):
        for param in self._params.values():
            param.zero_grad()

    def setattr(self, name, value):
        for param in self._params.values():
            setattr(param, name, value)

    def save(self, fname, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg_dict = {}
        for param in self._params.values():
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = param.data()
        nd_save(fname, arg_dict)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load

        loaded = nd_load(fname)
        params = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                if name not in params:
                    raise MXNetError("Parameter %s missing in file %s"
                                     % (name, fname))
        for name, val in params.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("Parameter %s in file is not in this "
                                     "dict" % name)
                continue
            p = self._params[name]
            if p._data is None:
                p.shape = tuple(val.shape)
                p.initialize(ctx=ctx)
            p.set_data(val)
