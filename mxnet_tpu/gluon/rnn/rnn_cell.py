"""RNN cells (reference ``python/mxnet/gluon/rnn/rnn_cell.py`` and the
symbolic ``python/mxnet/rnn/rnn_cell.py`` cell algebra: unroll,
Sequential/Residual/Zoneout/Bidirectional wrappers)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = tuple(batch_size if s == 0 else s
                          for s in info["shape"])
            states.append(nd.zeros(shape))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll over time (reference ``BaseRNNCell.unroll``)."""
        from ... import ndarray as nd

        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[0]
        else:
            batch = inputs.shape[layout.find("N")]
            seq = [nd.squeeze(s, axis=axis) if s.shape[axis] == 1 else s
                   for s in nd.split(inputs, num_outputs=length, axis=axis,
                                     squeeze_axis=True)]
            if length == 1:
                seq = [seq] if not isinstance(seq, list) else seq
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return self.hybrid_call(inputs, states)

    def hybrid_call(self, inputs, states):
        raise NotImplementedError


class RNNCell(RecurrentCell):
    _num_gates = 1  # LSTM=4, GRU=3: weights stack all gates (reference
    # cells do the same: i2h_weight is (num_gates*hidden, input))

    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * hidden_size, hidden_size),
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_size,), init="zeros",
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_size,), init="zeros",
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def _ensure(self, inputs, gates=None):
        nh = self._hidden_size * (gates or self._num_gates)
        if self.i2h_weight._data is None:
            self.i2h_weight._shape_from_data((nh, inputs.shape[-1]))
        if self.h2h_weight._data is None:
            self.h2h_weight._shape_from_data((nh, self._hidden_size))
        for b in (self.i2h_bias, self.h2h_bias):
            if b._data is None:
                b._shape_from_data((nh,))

    def hybrid_call(self, inputs, states):
        from ... import ndarray as nd

        self._ensure(inputs)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(),
                                self.i2h_bias.data(),
                                num_hidden=self._hidden_size)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(),
                                self.h2h_bias.data(),
                                num_hidden=self._hidden_size)
        out = nd.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RNNCell):
    _num_gates = 4

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size=input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def hybrid_call(self, inputs, states):
        from ... import ndarray as nd

        nh = self._hidden_size
        self._ensure(inputs, gates=4)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(),
                                self.i2h_bias.data(), num_hidden=nh * 4)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(),
                                self.h2h_bias.data(), num_hidden=nh * 4)
        gates = i2h + h2h
        slices = nd.split(gates, num_outputs=4, axis=1)
        in_gate = nd.sigmoid(slices[0])
        forget_gate = nd.sigmoid(slices[1])
        in_transform = nd.tanh(slices[2])
        out_gate = nd.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * nd.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RNNCell):
    _num_gates = 3

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size=input_size, **kwargs)

    def hybrid_call(self, inputs, states):
        from ... import ndarray as nd

        nh = self._hidden_size
        self._ensure(inputs, gates=3)
        prev = states[0]
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(),
                                self.i2h_bias.data(), num_hidden=nh * 3)
        h2h = nd.FullyConnected(prev, self.h2h_weight.data(),
                                self.h2h_bias.data(), num_hidden=nh * 3)
        i2h_r, i2h_z, i2h_n = nd.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = nd.split(h2h, num_outputs=3, axis=1)
        reset = nd.sigmoid(i2h_r + h2h_r)
        update = nd.sigmoid(i2h_z + h2h_z)
        next_h_tmp = nd.tanh(i2h_n + reset * h2h_n)
        next_h = (1. - update) * next_h_tmp + update * prev
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (reference ``SequentialRNNCell``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        info = []
        for cell in self._children.values():
            info.extend(cell.state_info(batch_size))
        return info

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def hybrid_call(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self.register_child(base_cell, "base_cell")

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class DropoutCell(_ModifierCell):
    def __init__(self, base_cell=None, rate=0.5, **kwargs):
        if base_cell is None:
            raise MXNetError("DropoutCell requires a base cell")
        super().__init__(base_cell, **kwargs)
        self._rate = rate

    def hybrid_call(self, inputs, states):
        from ... import ndarray as nd

        out, states = self.base_cell(inputs, states)
        if self._rate > 0:
            out = nd.Dropout(out, p=self._rate)
        return out, states


class ZoneoutCell(_ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def hybrid_call(self, inputs, states):
        from ... import ndarray as nd
        from ... import autograd

        out, next_states = self.base_cell(inputs, states)
        if autograd.is_training():
            if self._zo > 0 and self._prev_output is not None:
                mask = nd.Dropout(nd.ones_like(out), p=self._zo)
                out = nd.where(mask, out, self._prev_output)
            if self._zs > 0:
                next_states = [
                    nd.where(nd.Dropout(nd.ones_like(ns), p=self._zs),
                             ns, s)
                    for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(_ModifierCell):
    def hybrid_call(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.state_info(batch_size) + r.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.begin_state(batch_size, **kwargs) + \
            r.begin_state(batch_size, **kwargs)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        from ... import ndarray as nd

        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = nd.split(inputs, num_outputs=length, axis=axis,
                              squeeze_axis=True)
        batch = inputs[0].shape[0]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs, states[:nl],
                                        merge_outputs=False)
        r_out, r_states = r_cell.unroll(length, list(reversed(inputs)),
                                        states[nl:], merge_outputs=False)
        outs = [nd.concat(lo, ro, dim=1)
                for lo, ro in zip(l_out, reversed(r_out))]
        if merge_outputs:
            outs = nd.stack(*outs, axis=axis)
        return outs, l_states + r_states
