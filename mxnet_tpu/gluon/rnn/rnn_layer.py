"""Recurrent layers (reference ``python/mxnet/gluon/rnn/rnn_layer.py``:
RNN/LSTM/GRU over whole sequences; the reference dispatches to the fused
cuDNN RNN op — here the per-layer scan compiles through XLA, and the
symbolic fused ``RNN`` op (``mxnet_tpu/ops/rnn_ops.py``) uses lax.scan)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import Block
from .rnn_cell import RNNCell, LSTMCell, GRUCell, SequentialRNNCell, \
    BidirectionalCell

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(Block):
    def __init__(self, cell_factory, hidden_size, num_layers, layout,
                 dropout, bidirectional, input_size=0, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("layout must be TNC or NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        with self.name_scope():
            stack = SequentialRNNCell(prefix="")
            for i in range(num_layers):
                if bidirectional:
                    cell = BidirectionalCell(
                        cell_factory(hidden_size, prefix="l%d_" % i),
                        cell_factory(hidden_size, prefix="r%d_" % i))
                else:
                    cell = cell_factory(hidden_size, prefix="l%d_" % i)
                stack.add(cell)
            self._stack = stack
            self.register_child(stack, "stack")

    def state_info(self, batch_size=0):
        return self._stack.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self._stack.begin_state(batch_size, **kwargs)

    def forward(self, inputs, states=None):
        from ... import ndarray as nd

        t_axis = self._layout.find("T")
        n_axis = self._layout.find("N")
        length = inputs.shape[t_axis]
        batch = inputs.shape[n_axis]
        return_states = states is not None
        if states is None:
            states = self.begin_state(batch)
        outputs, out_states = self._stack.unroll(
            length, inputs, states, layout=self._layout, merge_outputs=True)
        if return_states:
            return outputs, out_states
        return outputs


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        def factory(h, prefix):
            return RNNCell(h, activation=activation, prefix=prefix)
        super().__init__(factory, hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        def factory(h, prefix):
            return LSTMCell(h, prefix=prefix)
        super().__init__(factory, hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        def factory(h, prefix):
            return GRUCell(h, prefix=prefix)
        super().__init__(factory, hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
