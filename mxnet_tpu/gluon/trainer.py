"""Gluon Trainer (reference ``python/mxnet/gluon/trainer.py``): applies an
Optimizer over a ParameterDict through a KVStore."""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device"):
        if isinstance(params, dict) or hasattr(params, "values"):
            params = list(params.values())
        # trainable params drive updates; ALL params (incl. grad-less
        # state like BatchNorm running stats) join dist_async averaging
        # rounds — per-shard moving stats would diverge without bound
        # otherwise (same stance as Module._async_params)
        self._all_params = list(params)
        self._params = [p for p in params if p.grad_req != "null"]
        self._scale = float(dict(optimizer_params or {}).get(
            "rescale_grad", 1.0))
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, opt.Optimizer):
            self._optimizer = optimizer
        else:
            param_idx2name = {i: p.name for i, p in enumerate(self._params)}
            self._optimizer = opt.create(
                optimizer, param_idx2name=param_idx2name, **optimizer_params)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def _init_kvstore(self):
        if self._kvstore_type and "dist" in str(self._kvstore_type):
            self._kvstore = kvs.create(self._kvstore_type)
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())
            if getattr(self._kvstore, "_is_async", False):
                # common starting point across hosts (the round
                # Module.init_optimizer runs)
                self._kvstore.sync_params(self._async_arrays())
        self._kv_initialized = True

    def _async_arrays(self):
        return [p.data() for p in self._all_params]

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step using gradients accumulated on the
        parameters (reference ``Trainer.step``: rescale 1/batch_size,
        kvstore push/pull, then updater)."""
        if not self._kv_initialized:
            self._init_kvstore()
        is_async = self._kvstore is not None and \
            getattr(self._kvstore, "_is_async", False)
        self._optimizer.rescale_grad = self._scale / batch_size
        live = []
        for i, p in enumerate(self._params):
            if p._grad is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "Parameter %s has no gradient; call backward first "
                        "or set grad_req" % p.name)
                continue
            live.append((i, p))
        if self._kvstore is not None and not is_async and live:
            # dist sync: ONE batched push/pull all-reduces every gradient
            # in a single DCN round trip instead of one per parameter
            # (same batching as Module.update), then update worker-side
            # (async updates are local — the round-trip would be a no-op
            # copy)
            keys = [i for i, _ in live]
            grads = [p._grad for _, p in live]
            self._kvstore.push(keys, grads, priority=0)
            self._kvstore.pull(keys, grads, priority=0)
        for i, p in live:
            self._updater(i, p._grad, p.data())
        if is_async:
            # dist_async: count this local update; a parameter-averaging
            # round fires every MXNET_ASYNC_SYNC_PERIOD updates.  Gluon
            # has no epoch loop to hook, so ALSO call sync_params() at
            # your epoch boundaries (docs/distributed.md).
            self._kvstore._async_tick(self._async_arrays)

    def sync_params(self):
        """dist_async parameter-averaging round across hosts (the
        epoch-boundary sync Module runs automatically; gluon training
        loops call this themselves).  No-op for sync kvstores and
        single-process runs."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and \
                getattr(self._kvstore, "_is_async", False):
            self._kvstore.sync_params(self._async_arrays())

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
