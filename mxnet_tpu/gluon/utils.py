"""Gluon utilities (reference ``python/mxnet/gluon/utils.py``)."""
from __future__ import annotations

import math

from ..base import MXNetError
from ..ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis (reference ``split_data``).  In SPMD mode a
    single sharded array usually replaces explicit splitting; this remains
    for API parity and host-side pipelines."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise MXNetError(
            "Too many slices for data with shape %s" % (data.shape,))
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "data size %d cannot be evenly split into %d slices"
            % (size, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * len(data.shape)
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = array(data, ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so the joint L2 norm is at most max_norm."""
    import numpy as np

    total = 0.0
    for arr in arrays:
        n = float((arr * arr).sum().asscalar())
        total += n
    total = math.sqrt(total)
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total
