"""Run-health sentinel: in-step numerical guards, skip/rollback policy,
and hang watchdogs.

A production run that *keeps going while silently diverging* — NaN/Inf
gradients, a loss blow-up, a wedged collective — burns the whole TPU
reservation without producing a model.  PR 2 made crashes survivable;
this subsystem makes bad numerics and stalls survivable:

* **In-step numerics** (``fused.TrainStep(health=...)``): the compiled
  step additionally computes a global gradient norm and an all-params
  non-finite flag *on device*.  Because the whole step is one fused XLA
  program, these are a handful of extra reductions fused into compute
  that is already reading the gradients — near-zero cost, zero extra
  host round-trips.  A non-finite step is *skipped inside the program*
  (``jnp.where`` keeps the old params/states/aux bit-exactly), so the
  clean path stays fully async.
* **Loss scaling** (:class:`DynamicLossScaler`): for low-precision
  ``compute_dtype`` runs the loss is multiplied by a dynamic scale
  before the backward and the gradients unscaled after; the scale and
  its clean-streak counter live as device scalars threaded through the
  step, so scale-up on clean streaks and scale-down+skip on overflow
  also happen in-program.
* **Policy engine** (:class:`HealthMonitor`): host-side EMA loss /
  grad-norm statistics over *lagged* device values — stats from step
  ``n - lag`` are realized while step ``n`` executes, so reading them
  never stalls the pipeline.  Per anomaly it applies the configured
  policy ladder ``warn`` → ``skip`` → ``rollback`` and raises
  :class:`~mxnet_tpu.base.TrainingDiverged` when recovery is exhausted.
* **Liveness**: :class:`StepWatchdog` (``MXNET_STEP_TIMEOUT_S``) dumps
  all-thread stacks plus the last health stats to an artifact and
  raises :class:`~mxnet_tpu.base.StepHung` in the training thread
  instead of hanging forever; :class:`RankHeartbeat`
  (``MXNET_HEARTBEAT_DIR``) lets a healthy rank *name* the dead peer
  when a bounded collective times out.

Everything is driven by ``MXNET_HEALTH_*`` env knobs (see
``docs/health_monitoring.md`` and ``docs/env_vars.md``) or the
``Module.fit(health=...)`` argument.
"""
from __future__ import annotations

import ctypes
import json
import os
import tempfile
import threading
import time

from .base import (MXNetError, StepHung, TrainingDiverged, get_env, logger)

__all__ = ["HealthMonitor", "DynamicLossScaler", "StepHealth",
           "StepWatchdog", "RankHeartbeat", "peer_report",
           "resolve_monitor", "TrainingDiverged", "StepHung"]

_POLICIES = ("warn", "skip", "rollback")

# thread-name prefixes the pytest leak guard (tests/conftest.py) checks
WATCHDOG_THREAD_PREFIX = "mxnet-step-watchdog"
HEARTBEAT_THREAD_PREFIX = "mxnet-heartbeat"


# ---------------------------------------------------------------------------
# loss scaling


class DynamicLossScaler:
    """Dynamic loss-scale schedule for low-precision runs.

    The *state* (current scale, clean-step streak) lives as device
    scalars threaded through the fused step; this object only carries
    the static schedule constants, which compile into the program:
    on overflow the scale halves (``backoff``) and the step is skipped;
    after ``growth_interval`` consecutive clean steps it doubles
    (``growth``), bounded to [``min_scale``, ``max_scale``].

    bf16 shares float32's exponent range, so TPU-default mixed precision
    rarely overflows — the scaler exists for fp16 ``compute_dtype`` runs
    and as a belt-and-braces guard for bf16 (``init_scale=1`` makes it a
    pure overflow detector).
    """

    def __init__(self, init_scale=2.0 ** 15, growth=2.0, backoff=0.5,
                 growth_interval=2000, min_scale=1.0, max_scale=2.0 ** 24):
        if init_scale <= 0 or growth < 1.0 or not 0 < backoff <= 1.0:
            raise MXNetError(
                "DynamicLossScaler needs init_scale > 0, growth >= 1, "
                "0 < backoff <= 1 (got %r, %r, %r)"
                % (init_scale, growth, backoff))
        self.init_scale = float(init_scale)
        self.growth = float(growth)
        self.backoff = float(backoff)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)

    @staticmethod
    def from_spec(spec):
        """Resolve a ``fit(loss_scale=...)`` / ``MXNET_LOSS_SCALE``
        value: ``'dynamic'`` → default dynamic scaler, a number → static
        scale (growth/backoff disabled), None/'' → no scaling."""
        if spec in (None, "", False):
            return None
        if isinstance(spec, DynamicLossScaler):
            return spec
        if isinstance(spec, str) and spec.lower() == "dynamic":
            return DynamicLossScaler()
        scale = float(spec)
        return DynamicLossScaler(init_scale=scale, growth=1.0, backoff=1.0,
                                 growth_interval=1 << 30, min_scale=scale,
                                 max_scale=scale)


class StepHealth:
    """Static health configuration compiled into a ``TrainStep``.

    ``skip_nonfinite`` — apply the zero-update skip inside the program
    when any gradient (or the loss) is non-finite; ``scaler`` — an
    optional :class:`DynamicLossScaler`.  The global grad norm and
    non-finite flag are always computed (that is what makes the step a
    sentinel); whether anything *acts* on them is policy."""

    def __init__(self, skip_nonfinite=True, scaler=None):
        self.skip_nonfinite = bool(skip_nonfinite)
        self.scaler = scaler


# ---------------------------------------------------------------------------
# policy engine


class HealthMonitor:
    """EMA loss/grad-norm statistics + per-anomaly policy ladder.

    ``tick(stats_ref)`` is called once per dispatched step with the
    *device references* of that step's health stats; the monitor holds
    them in a short queue and realizes only entries ``lag`` steps old —
    by then the producing step has long finished, so the host read
    costs nothing on the clean path.  ``observe`` classifies each
    realized step and returns the strongest pending action:

    * ``"ok"``   — nothing to do.
    * ``"warn"`` — anomaly logged (always happens, whatever the policy).
    * ``"skip"`` — a non-finite step; the device already applied the
      zero update, the monitor accounts for it and escalates after
      ``max_skips`` consecutive occurrences.
    * ``"rollback"`` — reload last-good + LR backoff (the trainer owns
      the mechanics); after ``max_rollbacks`` consecutive rollbacks
      with no clean progress in between, :class:`TrainingDiverged`.

    All thresholds default from ``MXNET_HEALTH_*`` env knobs so a
    launcher can tune a run without code changes.
    """

    def __init__(self, policy=None, loss_spike=None, grad_spike=None,
                 ema_decay=None, warmup=None, lag=None, max_skips=None,
                 max_rollbacks=None, lr_backoff=None, logger_=None):
        self.policy = policy if policy is not None else \
            get_env("MXNET_HEALTH_POLICY", "skip", str)
        if self.policy not in _POLICIES:
            raise MXNetError("health policy must be one of %s (got %r)"
                             % ("/".join(_POLICIES), self.policy))
        self.loss_spike = loss_spike if loss_spike is not None else \
            get_env("MXNET_HEALTH_LOSS_SPIKE", 10.0, float)
        self.grad_spike = grad_spike if grad_spike is not None else \
            get_env("MXNET_HEALTH_GRAD_SPIKE", 25.0, float)
        self.ema_decay = ema_decay if ema_decay is not None else \
            get_env("MXNET_HEALTH_EMA", 0.98, float)
        self.warmup = warmup if warmup is not None else \
            get_env("MXNET_HEALTH_WARMUP", 20, int)
        self.lag = lag if lag is not None else \
            get_env("MXNET_HEALTH_LAG", 2, int)
        self.max_skips = max_skips if max_skips is not None else \
            get_env("MXNET_HEALTH_MAX_SKIPS", 10, int)
        self.max_rollbacks = max_rollbacks if max_rollbacks is not None \
            else get_env("MXNET_HEALTH_MAX_ROLLBACKS", 3, int)
        self.lr_backoff = lr_backoff if lr_backoff is not None else \
            get_env("MXNET_HEALTH_LR_BACKOFF", 0.5, float)
        self.logger = logger_ or logger
        self._pending = []      # [(step, stats_ref)] not yet realized
        self.reset()

    # -- lifecycle ------------------------------------------------------
    def reset(self):
        """Forget statistics (fresh fit).  Rollback accounting survives
        ``soft_reset`` (post-rollback) but not this."""
        self._pending = []
        self.soft_reset()
        self.consecutive_rollbacks = 0
        self.total_rollbacks = 0
        self.total_skips = 0
        self.total_warnings = 0

    def soft_reset(self):
        """Drop the EMA state and streak counters but keep lifetime /
        rollback accounting — called after a rollback restores old
        params (the old EMA described the diverged trajectory)."""
        self._pending = []
        self.loss_ema = None
        self.grad_ema = None
        self.observed = 0
        self.consecutive_skips = 0
        self._clean_since_rollback = 0
        self.last_stats = None

    # -- per-step entry points -----------------------------------------
    def tick(self, stats_ref, step=None):
        """Queue this step's device stats; realize + classify entries
        ``lag`` steps old.  Returns the strongest action among the
        entries realized this call."""
        if stats_ref is not None:
            self._pending.append((step, stats_ref))
        action = "ok"
        while len(self._pending) > self.lag:
            s, ref = self._pending.pop(0)
            action = _stronger(action, self._realize(s, ref))
        return action

    def flush(self):
        """Realize every queued entry (epoch end / teardown).  Returns
        the strongest action found."""
        action = "ok"
        while self._pending:
            s, ref = self._pending.pop(0)
            action = _stronger(action, self._realize(s, ref))
        return action

    def _realize(self, step, ref):
        import numpy as np

        try:
            import jax

            vals = jax.device_get(ref)
        except Exception:
            vals = {k: np.asarray(v) for k, v in ref.items()}
        # a steps_per_call=K stats entry carries (K,) arrays: one
        # observation per inner step.  A stat a producer didn't measure
        # (e.g. the split path has no loss) must become None, not NaN —
        # observe() reads NaN as a non-finite step and would count every
        # healthy step as a skip
        has_loss = "loss" in vals
        has_gnorm = "grad_norm" in vals
        loss = np.atleast_1d(np.asarray(vals.get("loss", np.nan),
                                        "float64"))
        gnorm = np.atleast_1d(np.asarray(vals.get("grad_norm", np.nan),
                                         "float64"))
        bad = np.atleast_1d(np.asarray(vals.get("nonfinite", 0)))
        action = "ok"
        n = max(loss.shape[0] if has_loss else 1,
                gnorm.shape[0] if has_gnorm else 1, bad.shape[0])
        for k in range(n):
            action = _stronger(action, self.observe(
                step=step,
                loss=float(loss[min(k, loss.shape[0] - 1)])
                if has_loss else None,
                grad_norm=float(gnorm[min(k, gnorm.shape[0] - 1)])
                if has_gnorm else None,
                nonfinite=bool(bad[min(k, bad.shape[0] - 1)])))
        return action

    def observe(self, step=None, loss=None, grad_norm=None,
                nonfinite=False):
        """Classify one realized step.  Pure host logic — unit-testable
        without a device."""
        import math

        self.last_stats = {"step": step, "loss": loss,
                           "grad_norm": grad_norm,
                           "nonfinite": bool(nonfinite)}
        if nonfinite or (loss is not None and not math.isfinite(loss)) \
                or (grad_norm is not None
                    and not math.isfinite(grad_norm)):
            self.consecutive_skips += 1
            self.total_skips += 1
            self._clean_since_rollback = 0
            self.logger.warning(
                "health: non-finite step%s (consecutive %d/%d) — update "
                "skipped on device",
                "" if step is None else " %s" % (step,),
                self.consecutive_skips, self.max_skips)
            if self.policy == "warn":
                self.total_warnings += 1
                return "warn"
            if self.consecutive_skips >= self.max_skips:
                self.consecutive_skips = 0
                return self._escalate(
                    step, "%d consecutive non-finite steps"
                    % self.max_skips)
            return "skip"
        # finite step: update streaks first, then spike-check against
        # the EMA of the PREVIOUS steps
        self.consecutive_skips = 0
        self._clean_since_rollback += 1
        if self._clean_since_rollback >= max(1, self.warmup):
            self.consecutive_rollbacks = 0
        anomaly = None
        if self.observed >= self.warmup:
            if loss is not None and self.loss_ema is not None and \
                    abs(loss) > self.loss_spike * (abs(self.loss_ema)
                                                   + 1e-8):
                anomaly = "loss %.4g spiked > %gx EMA %.4g" % (
                    loss, self.loss_spike, self.loss_ema)
            elif grad_norm is not None and self.grad_ema is not None and \
                    grad_norm > self.grad_spike * (self.grad_ema + 1e-8):
                anomaly = "grad norm %.4g spiked > %gx EMA %.4g" % (
                    grad_norm, self.grad_spike, self.grad_ema)
        d = self.ema_decay
        if loss is not None:
            self.loss_ema = loss if self.loss_ema is None else \
                d * self.loss_ema + (1 - d) * loss
        if grad_norm is not None:
            self.grad_ema = grad_norm if self.grad_ema is None else \
                d * self.grad_ema + (1 - d) * grad_norm
        self.observed += 1
        if anomaly is None:
            return "ok"
        self.total_warnings += 1
        self.logger.warning(
            "health: %s%s", anomaly,
            "" if step is None else " at step %s" % (step,))
        if self.policy == "rollback":
            return self._escalate(step, anomaly)
        return "warn"

    def _escalate(self, step, reason):
        """Promote an exhausted-skip streak or a sustained spike to a
        rollback request — or to :class:`TrainingDiverged` when the
        policy forbids rollback or rollbacks are exhausted."""
        if self.policy != "rollback":
            raise TrainingDiverged(
                "training diverged: %s and policy %r cannot roll back "
                "(set MXNET_HEALTH_POLICY=rollback and pass "
                "fit(checkpoint=...) for automatic recovery)"
                % (reason, self.policy), reason=reason)
        if self.consecutive_rollbacks >= self.max_rollbacks:
            raise TrainingDiverged(
                "training diverged: %s after %d consecutive rollbacks "
                "(MXNET_HEALTH_MAX_ROLLBACKS) — the run does not recover "
                "from the last-good checkpoint; inspect the data stream "
                "and hyperparameters" % (reason,
                                         self.consecutive_rollbacks),
                reason=reason)
        self._last_anomaly = reason
        return "rollback"

    def note_rollback(self, step=None):
        """Account for a rollback the trainer just performed."""
        self.consecutive_rollbacks += 1
        self.total_rollbacks += 1
        self._clean_since_rollback = 0

    # -- diagnostics ----------------------------------------------------
    def snapshot(self):
        """JSON-able state for the watchdog dump / diagnose tooling."""
        return {
            "policy": self.policy,
            "observed": self.observed,
            "loss_ema": self.loss_ema,
            "grad_ema": self.grad_ema,
            "last_stats": self.last_stats,
            "consecutive_skips": self.consecutive_skips,
            "consecutive_rollbacks": self.consecutive_rollbacks,
            "total_skips": self.total_skips,
            "total_rollbacks": self.total_rollbacks,
            "total_warnings": self.total_warnings,
        }


def _stronger(a, b):
    order = ("ok", "warn", "skip", "rollback")
    return a if order.index(a) >= order.index(b) else b


def resolve_monitor(spec):
    """Normalize ``fit(health=...)`` / ``MXNET_HEALTH_MONITOR``:
    None → env switch, True → default monitor, a policy string →
    ``HealthMonitor(policy=...)``, an instance → itself, falsy → off."""
    if spec is None:
        spec = get_env("MXNET_HEALTH_MONITOR", False, bool)
    if not spec:
        return None
    if isinstance(spec, HealthMonitor):
        return spec
    if isinstance(spec, str):
        return HealthMonitor(policy=spec)
    return HealthMonitor()


# ---------------------------------------------------------------------------
# liveness: step watchdog


class StepWatchdog:
    """Daemon thread that fires when the training loop stops making
    progress.

    The loop calls :meth:`kick` at every dispatch boundary; if no kick
    arrives for ``timeout_s`` the watchdog (1) dumps all-thread stacks
    via ``faulthandler`` plus the last health stats to a JSON artifact
    under ``MXNET_HEALTH_DIR`` (and mirrors the stacks to stderr),
    then (2) delivers :class:`~mxnet_tpu.base.StepHung` into the
    training thread with ``PyThreadState_SetAsyncExc`` so the run fails
    diagnosably instead of hanging.  A hang blocked inside a C call
    surfaces when the call returns; for calls that never return, set
    ``MXNET_STEP_TIMEOUT_EXIT=1`` to hard-exit (code 70) one extra
    ``timeout_s`` after the dump — the stacks are already on disk.
    """

    def __init__(self, timeout_s, stats_cb=None, dump_dir=None,
                 target_thread=None):
        self.timeout_s = float(timeout_s)
        if self.timeout_s <= 0:
            raise MXNetError("StepWatchdog timeout must be > 0 (got %r)"
                             % timeout_s)
        self._stats_cb = stats_cb
        self._dump_dir = dump_dir or get_env(
            "MXNET_HEALTH_DIR", tempfile.gettempdir(), str)
        self._target = target_thread or threading.current_thread()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._last_kick = time.monotonic()
        self._note = "startup (no step dispatched yet)"
        self._paused = False
        self.fired = False
        self.dump_path = None
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="%s-%d" % (WATCHDOG_THREAD_PREFIX, os.getpid()))

    def start(self):
        self._thread.start()
        return self

    def kick(self, note=None):
        """Record progress (cheap: one lock + clock read).  Also resumes
        a paused watchdog — the first step of the next epoch rearms it."""
        with self._lock:
            self._last_kick = time.monotonic()
            self._paused = False
            if note is not None:
                self._note = note

    def pause(self):
        """Stop timing until the next :meth:`kick` — for epoch tails
        (eval pass, checkpoint write, callbacks) whose duration is
        unrelated to per-step progress."""
        with self._lock:
            self._paused = True

    def stop(self, join_timeout=5.0):
        self._stop.set()
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=join_timeout)

    @property
    def alive(self):
        return self._thread.is_alive()

    def _run(self):
        # poll at a fraction of the timeout: the watchdog must notice a
        # stall within ~timeout + poll ("grace"), not 2x timeout
        poll = max(0.05, min(self.timeout_s / 4.0, 2.0))
        while not self._stop.wait(poll):
            with self._lock:
                if self._paused:
                    self._last_kick = time.monotonic()
                    continue
                stalled = time.monotonic() - self._last_kick
                note = self._note
            if stalled >= self.timeout_s:
                self._fire(stalled, note)
                return

    def _fire(self, stalled, note):
        self.fired = True
        try:
            self.dump_path = self._dump(stalled, note)
        except Exception as e:  # the dump must never mask the raise
            logger.error("watchdog dump failed: %s", e)
        msg = ("training step made no progress for %.1fs "
               "(MXNET_STEP_TIMEOUT_S=%.0f) at %s — a wedged device "
               "call, deadlocked collective, or stuck input pipeline; "
               "all-thread stacks dumped to %r (pretty-print with "
               "tools/diagnose.py)"
               % (stalled, self.timeout_s, note, self.dump_path))
        logger.critical(msg)
        # stash the details where the raising thread can find them BEFORE
        # delivery: SetAsyncExc instantiates the class with no arguments,
        # and the target can catch it and read last_hang_details()
        # immediately
        _last_hang["msg"] = msg
        _last_hang["note"] = note
        _last_hang["dump_path"] = self.dump_path
        delivered = _async_raise(self._target, StepHung)
        if not delivered:
            _last_hang.clear()
        if get_env("MXNET_STEP_TIMEOUT_EXIT", False, bool):
            # a thread wedged inside C never sees the async exception;
            # give it one more timeout, then fail the process loudly —
            # the diagnostics are already on disk
            if not self._stop.wait(self.timeout_s):
                logger.critical(
                    "watchdog: thread still wedged %.0fs after the "
                    "dump; hard-exiting 70", self.timeout_s)
                os._exit(70)

    def _dump(self, stalled, note):
        import faulthandler
        import sys

        os.makedirs(self._dump_dir, exist_ok=True)
        path = os.path.join(
            self._dump_dir,
            "watchdog-%d-%d.json" % (os.getpid(), int(time.time())))
        with tempfile.TemporaryFile(mode="w+") as tf:
            faulthandler.dump_traceback(file=tf, all_threads=True)
            tf.seek(0)
            stacks = tf.read()
        stats = None
        if self._stats_cb is not None:
            try:
                stats = self._stats_cb()
            except Exception as e:
                stats = {"error": "stats_cb failed: %s" % e}
        payload = {
            "kind": "mxnet_tpu-watchdog-dump",
            "pid": os.getpid(),
            "time": time.time(),
            "stalled_s": stalled,
            "timeout_s": self.timeout_s,
            "note": note,
            "health": stats,
            "traceback": stacks,
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
        print("WATCHDOG: no step progress for %.1fs at %s; stacks:\n%s"
              % (stalled, note, stacks), file=sys.stderr)
        sys.stderr.flush()
        return path


# details of the most recent watchdog firing, read by the zero-arg
# StepHung that PyThreadState_SetAsyncExc constructs
_last_hang = {}


def last_hang_details():
    return dict(_last_hang)


def _async_raise(thread, exc_type):
    """Deliver ``exc_type`` asynchronously into ``thread``.  Returns
    True when the interpreter accepted the request (the exception lands
    at the thread's next bytecode boundary)."""
    tid = getattr(thread, "ident", None)
    if tid is None or not thread.is_alive():
        return False
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc_type))
    if res > 1:  # undefined state: revoke
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid),
                                                   None)
        return False
    return res == 1


# ---------------------------------------------------------------------------
# liveness: rank heartbeats


class RankHeartbeat:
    """Periodic per-rank liveness beacons over a shared directory.

    Each rank rewrites ``<dir>/heartbeat_rank<k>.json`` every
    ``interval_s``; when a bounded collective times out, the survivor
    reads every peer's beacon and *names* the dead/stale rank in the
    error instead of timing out anonymously.  The directory
    (``MXNET_HEARTBEAT_DIR``) is typically the same shared filesystem
    the checkpoints live on."""

    def __init__(self, directory, rank, num_workers, interval_s=None):
        self.directory = str(directory)
        self.rank = int(rank)
        self.num_workers = int(num_workers)
        self.interval_s = interval_s if interval_s is not None else \
            get_env("MXNET_HEARTBEAT_INTERVAL_S", 5.0, float)
        self._write_failing = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="%s-rank%d" % (HEARTBEAT_THREAD_PREFIX, self.rank))

    @staticmethod
    def path_for(directory, rank):
        return os.path.join(str(directory), "heartbeat_rank%d.json" % rank)

    @staticmethod
    def maybe_start(rank, num_workers):
        """Start a heartbeat when ``MXNET_HEARTBEAT_DIR`` is configured
        and the job is actually multi-rank; otherwise None."""
        directory = get_env("MXNET_HEARTBEAT_DIR", "", str)
        if not directory or num_workers <= 1:
            return None
        hb = RankHeartbeat(directory, rank, num_workers)
        hb.start()
        return hb

    def start(self):
        os.makedirs(self.directory, exist_ok=True)
        self._beat()
        self._thread.start()
        return self

    def stop(self, join_timeout=5.0):
        self._stop.set()
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=join_timeout)

    @property
    def alive(self):
        return self._thread.is_alive()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._beat()

    def _beat(self):
        path = self.path_for(self.directory, self.rank)
        tmp = "%s.tmp-%d" % (path, os.getpid())
        try:
            with open(tmp, "w") as f:
                json.dump({"rank": self.rank, "pid": os.getpid(),
                           "time": time.time()}, f)
            os.replace(tmp, path)
            if self._write_failing:
                self._write_failing = False
                logger.warning("heartbeat writes recovered (rank %d)",
                               self.rank)
        except OSError as e:  # heartbeats must never kill training
            # rate-limited: a full disk re-fails EVERY beat — log the
            # transition once, then stay quiet until it recovers
            if not self._write_failing:
                self._write_failing = True
                logger.warning(
                    "heartbeat write failed: %s (suppressing repeats "
                    "until writes recover)", e)
            else:
                logger.debug("heartbeat write still failing: %s", e)
            try:
                os.remove(tmp)
            except OSError:
                pass


class PeerScan(list):
    """Result of :func:`stale_peers`: a list of ``(rank, description)``
    pairs plus a scan ``error`` field, so "empty because every peer is
    live" is distinguishable from "empty because the heartbeat
    directory could not be read at all" (permissions lost, mount gone).
    Existing truthiness/iteration callers are unchanged; diagnostics
    that would otherwise blame N peers for a local I/O failure check
    ``unreadable`` first."""

    def __init__(self, items=(), error=None):
        super().__init__(items)
        self.error = None if error is None else str(error)

    @property
    def unreadable(self):
        return self.error is not None


def stale_peers(directory, num_workers, stale_s=None, self_rank=None,
                now=None):
    """Name the ranks whose heartbeat is stale or missing.

    Returns a :class:`PeerScan` of ``(rank, description)`` — empty when
    every peer is live (or heartbeats are unconfigured).  A directory
    that exists but cannot be read yields a typed EMPTY scan with
    ``error`` set instead of misreporting every peer as dead: the
    failure is local, and acting on it (e.g. an elastic shrink) would
    evict healthy ranks."""
    if not directory:
        return PeerScan()
    if stale_s is None:
        stale_s = get_env("MXNET_HEARTBEAT_STALE_S",
                          3 * get_env("MXNET_HEARTBEAT_INTERVAL_S", 5.0,
                                      float), float)
    now = time.time() if now is None else now
    if os.path.exists(directory):
        try:
            os.listdir(directory)
        except OSError as e:
            return PeerScan(error="heartbeat directory %r exists but is "
                                  "unreadable: %s" % (directory, e))
    out = []
    for rank in range(int(num_workers)):
        if self_rank is not None and rank == self_rank:
            continue
        path = RankHeartbeat.path_for(directory, rank)
        try:
            with open(path) as f:
                beat = json.load(f)
            age = now - float(beat.get("time", 0))
            if age > stale_s:
                out.append((rank, "rank %d (pid %s) last heartbeat "
                            "%.1fs ago" % (rank, beat.get("pid", "?"),
                                           age)))
        except (OSError, ValueError):
            out.append((rank, "rank %d never wrote a heartbeat under %r"
                        % (rank, directory)))
    return PeerScan(out)


def peer_report(num_workers, self_rank=None):
    """One-line peer liveness summary for timeout diagnostics, or ''
    when heartbeats are unconfigured."""
    directory = get_env("MXNET_HEARTBEAT_DIR", "", str)
    if not directory or num_workers <= 1:
        return ""
    dead = stale_peers(directory, num_workers, self_rank=self_rank)
    if getattr(dead, "unreadable", False):
        return "; peer heartbeats unknown: %s" % dead.error
    if not dead:
        return ("; peer heartbeats under %r are all current — the "
                "stall is local (device queue or network), not a dead "
                "peer" % directory)
    return "; dead/stale peers: " + ", ".join(d for _, d in dead)
