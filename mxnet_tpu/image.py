"""Image IO + augmentation + record-backed iterators.

Reference surface: ``python/mxnet/image/image.py`` (pure-Python ImageIter
+ augmenter chain) and the C++ ``ImageRecordIter``
(``src/io/iter_image_recordio_2.cc:513`` — sharded multithreaded decode,
``src/io/image_aug_default.cc`` — the default augmenter chain).

TPU-native re-design: decode and augmentation are host-side work whose
only job is to keep the device fed, so the pipeline is numpy/PIL with a
thread pool for decode (PIL JPEG decode releases the GIL) feeding the
existing ``PrefetchingIter`` double-buffer — the role of the reference's
``dmlc::ThreadedIter``.  Arrays are RGB (the reference's cv2 path is BGR;
consistent within this library).  Sharded reading for multi-host uses the
same ``part_index``/``num_parts`` contract as the reference C iter.
"""
from __future__ import annotations

import functools
import io as _pyio
import os
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter
from . import recordio

__all__ = ["imdecode", "imread", "imresize", "copyMakeBorder",
           "scale_down", "resize_short", "fixed_crop", "random_crop",
           "center_crop", "color_normalize", "random_size_crop",
           "Augmenter", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
           "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter",
           "RecordImageLoader"]

_PIL_INTERP = None


def _interp(method):
    """Map the reference's cv2 interpolation codes onto PIL resamplers."""
    global _PIL_INTERP
    if _PIL_INTERP is None:
        from PIL import Image

        _PIL_INTERP = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BOX,
                       3: Image.BICUBIC, 4: Image.LANCZOS}
    if method == 10:
        method = random.choice((0, 1, 2, 3, 4))
    if method == 9:
        method = 2
    return _PIL_INTERP.get(method, _PIL_INTERP[1])


# -- host image ops (reference src/io/image_io.cc registers these as ops) ---

def imdecode(buf, to_rgb=1, flag=1):
    """Decode an encoded image buffer to an HWC uint8 array (reference
    ``mx.image.imdecode`` / the ``_cvimdecode`` op)."""
    from PIL import Image

    img = Image.open(_pyio.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def imread(filename, flag=1):
    """Read an image file (reference ``_cvimread``)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag)


def imresize(src, w, h, interp=2):
    """Resize to exactly (h, w) (reference ``_cvimresize``)."""
    from PIL import Image

    img = Image.fromarray(np.asarray(src, dtype=np.uint8).squeeze())
    return np.asarray(img.resize((w, h), _interp(interp))).reshape(
        (h, w) + ((src.shape[2],) if src.ndim == 3 else ()))


def copyMakeBorder(src, top, bot, left, right, fill_value=0):
    """Pad with a constant border (reference ``_cvcopyMakeBorder``)."""
    pads = [(top, bot), (left, right)] + [(0, 0)] * (src.ndim - 2)
    return np.pad(src, pads, constant_values=fill_value)


# -- functional augment helpers (reference image.py:139-480) ----------------

def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32)
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, min_area, ratio, interp=2):
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = random.uniform(min_area, 1.0) * area
        ar = random.uniform(*ratio)
        new_w = int(round((target_area * ar) ** 0.5))
        new_h = int(round((target_area / ar) ** 0.5))
        if random.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return random_crop(src, size, interp)


# -- augmenter classes (reference image.py:482-860) -------------------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size, self.min_area, self.ratio, self.interp = \
            size, min_area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return (src.astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum() * (3.0 / src.size)
        return src * alpha + gray * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src.astype(np.float32) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __call__(self, src):
        return src.astype(np.float32)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Build the default augmenter chain (reference ``CreateAugmenter``,
    matching ``src/io/image_aug_default.cc`` order: resize → crop →
    mirror → color jitter → pca noise → cast → normalize)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08,
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    jitters = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if jitters:
        auglist.append(RandomOrderAug(jitters))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


@functools.lru_cache(maxsize=16)
def _batch_tail_fn(mean_t, std_t):
    """Jitted device tail of the augmenter chain: NHWC uint8 batch ->
    NCHW fp32 (+ per-channel affine normalize).  Moving cast/transpose/
    normalize OFF the host matters on small hosts: the per-image
    float32 cast and strided transpose otherwise dominate decode."""
    import jax
    import jax.numpy as jnp

    def f(x):
        x = jnp.transpose(x, (0, 3, 1, 2)).astype(jnp.float32)
        if mean_t is not None:
            x = x - jnp.asarray(mean_t, jnp.float32).reshape(1, -1, 1, 1)
        if std_t is not None:
            x = x / jnp.asarray(std_t, jnp.float32).reshape(1, -1, 1, 1)
        return x

    return jax.jit(f)


# augmenters that only move/select pixels: safe to run on a uint8 image
# (resize interpolation rounds back into [0, 255]).  Anything else —
# jitters, lighting, user Augmenter subclasses — produces float values
# the uint8 fast path would wrap modulo 256 on the way into the batch
# buffer.
_SHAPE_ONLY_AUGS = (ResizeAug, ForceResizeAug, RandomCropAug,
                    RandomSizedCropAug, CenterCropAug, HorizontalFlipAug)


def _uint8_safe(aug):
    if isinstance(aug, RandomOrderAug):
        return all(_uint8_safe(t) for t in aug.ts)
    return type(aug) in _SHAPE_ONLY_AUGS


def _split_device_tail(aug_list):
    """If the chain ends with CastAug [+ ColorNormalizeAug] and every
    remaining host augmenter is shape-only (crop/resize/flip — nothing
    float-producing), the tail runs on DEVICE per batch and the host
    path stays uint8.  Returns (host_augs, mean, std, fast) —
    fast=False keeps the classic per-image float path (a float-producing
    jitter before CastAug would otherwise have its output wrapped modulo
    256 by the uint8 batch buffer)."""
    host = list(aug_list)
    mean = std = None
    if host and isinstance(host[-1], ColorNormalizeAug):
        mean, std = host[-1].mean, host[-1].std
        host = host[:-1]
    elif host and isinstance(host[-1], CastAug):
        host = host[:-1]
        if all(_uint8_safe(a) for a in host):
            return host, None, None, True
        return list(aug_list), None, None, False
    else:
        return list(aug_list), None, None, False
    if host and isinstance(host[-1], CastAug):
        host = host[:-1]
        if not all(_uint8_safe(a) for a in host):
            return list(aug_list), None, None, False
        m = None if mean is None else tuple(float(v) for v in mean)
        s = None if std is None else tuple(float(v) for v in std)
        return host, m, s, True
    return list(aug_list), None, None, False


class RecordImageLoader:
    """Picklable per-sample decode+augment kernel — the unit of work
    shared by :class:`ImageIter` (thread pool) and
    :class:`~mxnet_tpu.data_service.DataServiceIter` (process pool).

    ``__call__(i)`` decodes sample ``i`` of ``keys`` and returns
    ``(image, label)`` — uint8 HWC when the augmenter chain's
    cast/normalize tail runs on device (``fast``), float32 CHW otherwise.
    Pickling drops the (unpicklable) shared read lock, and the recordio
    handle inside reopens at its saved offset on unpickle
    (``MXRecordIO.__setstate__``); after a *fork* the handle still shares
    the parent's file offset, so process-pool workers call
    :meth:`worker_init` to re-open it privately.
    """

    def __init__(self, data_shape, record=None, imglist=None, keys=None,
                 aug_list=None, label_width=1, data_name="data",
                 label_name="softmax_label"):
        if record is None and imglist is None:
            raise MXNetError("RecordImageLoader needs record= or imglist=")
        self.record = record
        self.imglist = imglist
        if keys is None:
            keys = list(record.keys) if record is not None \
                else list(range(len(imglist)))
        self.keys = list(keys)
        self.aug_list = CreateAugmenter(data_shape) if aug_list is None \
            else aug_list
        (self.host_augs, self.tail_mean, self.tail_std,
         self.fast) = _split_device_tail(self.aug_list)
        self.sample_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self._lock = None

    def __len__(self):
        return len(self.keys)

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_lock"] = None
        return d

    def worker_init(self):
        """Per-process re-arm for decode workers: a private file offset
        (a forked child shares the parent's) and no lock (the worker is
        single-threaded)."""
        self._lock = None
        if self.record is not None:
            self.record._reopen_read()

    def _read(self, key):
        if self.record is not None:
            if self._lock is not None:
                with self._lock:
                    raw = self.record.read_idx(key)
            else:
                raw = self.record.read_idx(key)
            header, img = recordio.unpack_img(raw)
            return img, header.label
        label, fname = self.imglist[key]
        return imread(fname), label

    def load_float(self, key):
        """Classic path: full augmenter chain per image, float32 CHW."""
        img, label = self._read(key)
        for aug in self.aug_list:
            img = aug(img)
        img = np.asarray(img, np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        c, h, w = self.sample_shape
        if img.shape[:2] != (h, w):
            img = imresize(img.astype(np.uint8), w, h)
            img = np.asarray(img, np.float32).reshape(h, w, c)
        return img.transpose(2, 0, 1), np.asarray(label, np.float32)

    def load_uint8(self, key):
        """Fast path: decode + host (shape-only) augs, uint8 HWC out; the
        cast/transpose/normalize tail runs on device per batch."""
        img, label = self._read(key)
        for aug in self.host_augs:
            img = aug(img)
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        c, h, w = self.sample_shape
        if img.shape[:2] != (h, w):
            img = imresize(img.astype(np.uint8), w, h)
            img = np.asarray(img).reshape(h, w, c)
        return img.astype(np.uint8, copy=False), \
            np.asarray(label, np.float32)

    def __call__(self, i):
        key = self.keys[int(i)]
        return self.load_uint8(key) if self.fast else self.load_float(key)


class ImageIter(DataIter):
    """Image iterator over RecordIO (or an image list) with augmenters —
    the reference's Python ``ImageIter``, doubling as the backing for
    ``io.ImageRecordIter`` (C iter ``iter_image_recordio_2.cc:513``).

    Supports ``part_index``/``num_parts`` sharding (each worker reads a
    contiguous slice of the key space, like ``dmlc::InputSplit``),
    shuffling, and a thread pool for decode+augment.

    With ``seed=`` the per-epoch shuffle order becomes a pure function of
    ``(seed, epoch)`` (counter-based permutation over this shard's keys),
    which makes the iterator *seekable*: ``seek(epoch, nbatch)`` jumps in
    O(1) instead of replaying.  Unseeded shuffle keeps the legacy
    global-``random`` in-place shuffle.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imgidx=None, path_imglist=None,
                 path_root="", shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", last_batch_handle="pad",
                 num_threads=4, seed=None, **kwargs):
        super().__init__(batch_size)
        if num_parts < 1 or not 0 <= part_index < num_parts:
            raise MXNetError("invalid part_index %d / num_parts %d"
                             % (part_index, num_parts))
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.record = None
        self.imglist = None
        if path_imgrec:
            # a missing .idx sidecar is rebuilt by the native frame
            # scanner inside MXIndexedRecordIO.open
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
            self.record = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                    "r")
            keys = list(self.record.keys)
            if not keys:
                raise MXNetError("no records found in %s" % path_imgrec)
        elif path_imglist or imglist is not None:
            if path_imglist:
                imglist = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        imglist.append((
                            [float(x) for x in parts[1:-1]], parts[-1]))
            self.imglist = [(np.asarray(lbl, np.float32),
                             os.path.join(path_root, fname))
                            for lbl, fname in imglist]
            keys = list(range(len(self.imglist)))
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist "
                             "or imglist")
        # dmlc::InputSplit-style contiguous sharding
        total = len(keys)
        begin = total * part_index // num_parts
        end = total * (part_index + 1) // num_parts
        self.keys = keys[begin:end]
        if not self.keys:
            raise MXNetError("empty shard %d/%d (%d records)"
                             % (part_index, num_parts, total))
        self.aug_list = CreateAugmenter(data_shape) if aug_list is None \
            else aug_list
        # the per-sample decode kernel is a standalone picklable object
        # (shared with the multiprocess data service); device-tail fast
        # path: host stays uint8, cast/transpose/normalize run jitted on
        # device per BATCH
        self._loader = RecordImageLoader(
            data_shape, record=self.record, imglist=self.imglist,
            keys=self.keys, aug_list=self.aug_list,
            label_width=label_width, data_name=data_name,
            label_name=label_name)
        self._host_augs = self._loader.host_augs
        self._tail_mean = self._loader.tail_mean
        self._tail_std = self._loader.tail_std
        self._fast_tail = self._loader.fast
        # a 1-core host gains nothing from a decode pool (GIL thrash
        # with the consumer); run decode inline there
        self._serial = num_threads <= 1 or (os.cpu_count() or 1) <= 1
        self._num_threads = num_threads
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self._closed = False
        # record seek+read must be atomic (one shared file handle across
        # the decode pool); decode/augment run outside the lock
        self._rec_lock = threading.Lock()
        self._loader._lock = self._rec_lock
        self._seed = seed
        self._epoch = -1  # reset() below starts epoch 0
        self.cur = 0
        self._order = list(self.keys)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape,
                         np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, np.float32)]

    def _reorder(self):
        """Recompute this epoch's sample order.  Seeded: a counter-based
        permutation keyed by ``(seed, epoch)`` — position-addressable,
        so ``seek`` can land anywhere.  Unseeded: the legacy in-place
        ``random.shuffle`` (history-dependent, not seekable)."""
        if not self.shuffle:
            return
        if self._seed is not None:
            from .data_service import epoch_permutation

            perm = epoch_permutation(self._seed, self._epoch,
                                     len(self.keys))
            self._order = [self.keys[i] for i in perm]
        else:
            random.shuffle(self._order)

    def _reopen_pool(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=self._num_threads)
        self._closed = False

    def reset(self):
        self._epoch += 1
        self._reorder()
        if self.record is not None:
            self.record.reset()
        self.cur = 0
        self._reopen_pool()

    def seekable(self):
        return (not self.shuffle) or self._seed is not None

    def seek(self, epoch, nbatch):
        """O(1) jump to ``(epoch, nbatch)``: recompute the seeded epoch
        permutation and place the cursor via the recordio index — no
        batches decoded or replayed."""
        if not self.seekable():
            raise MXNetError(
                "ImageIter with shuffle=True but no seed= is not "
                "seekable; pass seed= for position-addressable epochs")
        self._epoch = int(epoch)
        self._reorder()
        if not self.shuffle:
            self._order = list(self.keys)
        self.cur = int(nbatch) * self.batch_size
        self._reopen_pool()

    def close(self, timeout=5):
        """Shut the decode pool down deterministically (same
        join-with-timeout contract as the prefetchers'
        ``_ThreadedPrefetchTeardown.close``): cancel queued work, join
        the pool threads with ``timeout``, warn if any survive.  The
        iterator reports exhaustion until ``reset``/``seek`` (which
        recreate the pool)."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
            threads = list(getattr(pool, "_threads", ()))
            deadline = time.monotonic() + timeout
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            if any(t.is_alive() for t in threads):
                import logging

                logging.warning("ImageIter decode pool did not exit "
                                "within %ss on close()", timeout)
        self._closed = True

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def _load_one(self, key):
        return self._loader.load_float(key)

    def _load_one_uint8(self, key):
        """Fast-path loader: decode + host (shape-only) augs, uint8 HWC
        out; the cast/transpose/normalize tail runs on device."""
        return self._loader.load_uint8(key)

    def next(self):
        if self._closed or self.cur >= len(self._order):
            raise StopIteration
        want = self._order[self.cur:self.cur + self.batch_size]
        pad = self.batch_size - len(want)
        if pad:
            if self.last_batch_handle == "discard":
                self.cur = len(self._order)
                raise StopIteration
            want = want + self._order[:pad]
        self.cur += self.batch_size
        from .ndarray import NDArray, array

        loader = self._load_one_uint8 if self._fast_tail else \
            self._load_one
        if self._serial:
            loaded = [loader(k) for k in want]
        else:
            loaded = list(self._pool.map(loader, want))
        if self._fast_tail:
            c, h, w = self.data_shape
            imgs = np.empty((self.batch_size, h, w, c), np.uint8)
            for i, (im, _l) in enumerate(loaded):
                imgs[i] = im
            labels = np.stack([l for _, l in loaded])
            dev = array(imgs)
            out = _batch_tail_fn(self._tail_mean, self._tail_std)(
                dev._data)
            data_nd = NDArray(out, dev.context)
        else:
            data_nd = array(np.stack([x[0] for x in loaded]))
            labels = np.stack([x[1] for x in loaded])
        if self.label_width == 1:
            labels = labels.reshape(self.batch_size, -1)[:, 0]
        return DataBatch(data=[data_nd], label=[array(labels)],
                         pad=pad, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            self._next_batch = None
            return False

    def getdata(self):
        return self._next_batch.data

    def getlabel(self):
        return self._next_batch.label

    def getindex(self):
        return self._next_batch.index

    def getpad(self):
        return self._next_batch.pad


# detection pipeline (reference image/detection.py) — imported last to
# avoid a circular import, re-exported here so the reference's
# ``mx.image.ImageDetIter`` spelling works
from .image_detection import (ImageDetIter, CreateDetAugmenter,  # noqa: E402
                              DetAugmenter, DetBorrowAug,
                              DetHorizontalFlipAug, DetRandomCropAug,
                              DetRandomPadAug, DetRandomSelectAug)
