"""Detection data pipeline: box-aware augmenters + ``ImageDetIter``
(reference ``python/mxnet/image/detection.py`` and the det augmenter
chain ``src/io/image_det_aug_default.cc``).

Labels are object lists ``(cls, x1, y1, x2, y2)`` with corner coordinates
normalized to [0, 1].  Geometric augmenters transform the boxes with the
pixels (flip mirrors x; crop re-normalizes into the crop window and drops
objects whose center leaves it; pad re-normalizes outward).  Batches pad
the object axis with ``-1`` rows to the iterator's ``max_objects`` —
static shapes for XLA, the same padding contract the contrib MultiBox*
ops consume.
"""
from __future__ import annotations

import random

import numpy as np

from .base import MXNetError
from .image import (Augmenter, CreateAugmenter, ImageIter, fixed_crop,
                    imresize)
from .io import DataBatch, DataDesc

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetRandomSelectAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base: ``__call__(src, label) -> (src, label)``; label (N, 5)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only augmenter (color jitter etc.) — boxes pass
    through (reference ``DetBorrowAug``)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Sample a crop window satisfying the min-overlap constraint and
    re-normalize surviving boxes (objects keep membership by center,
    reference ``DetRandomCropAug``)."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _overlap(self, box, crop):
        ix1 = max(box[0], crop[0]); iy1 = max(box[1], crop[1])
        ix2 = min(box[2], crop[2]); iy2 = min(box[3], crop[3])
        iw = max(0.0, ix2 - ix1); ih = max(0.0, iy2 - iy1)
        area = (box[2] - box[0]) * (box[3] - box[1])
        return iw * ih / area if area > 0 else 0.0

    def __call__(self, src, label):
        h, w = src.shape[:2]
        valid = label[label[:, 0] >= 0]
        for _ in range(self.max_attempts):
            scale = random.uniform(*self.area_range)
            ratio = random.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(scale * ratio))
            ch = min(1.0, np.sqrt(scale / ratio))
            cx = random.uniform(0, 1 - cw)
            cy = random.uniform(0, 1 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if len(valid) and max(
                    self._overlap(b[1:5], crop) for b in valid) \
                    < self.min_object_covered:
                continue
            # keep objects whose center is inside the crop
            out = []
            for b in valid:
                ctr_x = (b[1] + b[3]) / 2
                ctr_y = (b[2] + b[4]) / 2
                if not (crop[0] <= ctr_x <= crop[2]
                        and crop[1] <= ctr_y <= crop[3]):
                    continue
                nb = b.copy()
                nb[1] = (max(b[1], crop[0]) - cx) / cw
                nb[2] = (max(b[2], crop[1]) - cy) / ch
                nb[3] = (min(b[3], crop[2]) - cx) / cw
                nb[4] = (min(b[4], crop[3]) - cy) / ch
                out.append(nb)
            if len(valid) and not out:
                continue
            x0, y0 = int(cx * w), int(cy * h)
            cw_px, ch_px = max(1, int(cw * w)), max(1, int(ch * h))
            src = fixed_crop(src, x0, y0, cw_px, ch_px)
            label = np.asarray(out, np.float32).reshape(-1, 5) if out \
                else np.zeros((0, 5), np.float32)
            return src, label
        return src, valid.reshape(-1, 5)


class DetRandomPadAug(DetAugmenter):
    """Zoom out: place the image on a larger canvas and re-normalize
    boxes inward (reference ``DetRandomPadAug``)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.pad_val = pad_val

    def __call__(self, src, label):
        h, w = src.shape[:2]
        scale = random.uniform(*self.area_range)
        ratio = random.uniform(*self.aspect_ratio_range)
        nw = max(1.0, np.sqrt(scale * ratio))
        nh = max(1.0, np.sqrt(scale / ratio))
        ox = random.uniform(0, nw - 1)
        oy = random.uniform(0, nh - 1)
        canvas = np.empty((int(h * nh), int(w * nw), src.shape[2]),
                          src.dtype)
        canvas[...] = np.asarray(self.pad_val, src.dtype)
        x0, y0 = int(ox * w), int(oy * h)
        canvas[y0:y0 + h, x0:x0 + w] = src
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] + ox) / nw
        label[valid, 3] = (label[valid, 3] + ox) / nw
        label[valid, 2] = (label[valid, 2] + oy) / nh
        label[valid, 4] = (label[valid, 4] + oy) / nh
        return canvas, label


class DetRandomSelectAug(DetAugmenter):
    """Pick one augmenter at random (or skip) — reference
    ``DetRandomSelectAug``."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return random.choice(self.aug_list)(src, label)


class _DetForceResize(DetAugmenter):
    def __init__(self, w, h, interp=2):
        self.w, self.h, self.interp = w, h, interp

    def __call__(self, src, label):
        return imresize(np.asarray(src, np.uint8), self.w, self.h,
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), pad_val=(127, 127, 127),
                       **kwargs):
    """The default det augmenter chain (reference
    ``CreateDetAugmenter`` / ``image_det_aug_default.cc``): random
    crop/pad (each taken with its probability), mirror, forced resize to
    ``data_shape``, then the borrowed color/normalize augmenters."""
    auglist = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])))
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(_DetForceResize(data_shape[2], data_shape[1],
                                   inter_method))
    for aug in CreateAugmenter(data_shape, brightness=brightness,
                               contrast=contrast, saturation=saturation,
                               mean=mean, std=std):
        name = aug.__class__.__name__
        if name in ("BrightnessJitterAug", "ContrastJitterAug",
                    "SaturationJitterAug", "ColorNormalizeAug",
                    "CastAug"):
            auglist.append(DetBorrowAug(aug))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference ``ImageDetIter``): labels become a
    fixed ``(batch, max_objects, 5)`` tensor, ``-1``-padded.

    Record/list labels may be flat ``k*5`` floats, or the reference's
    headed format ``[A, B, ...]`` (A = header length, B = object width)."""

    def __init__(self, batch_size, data_shape, max_objects=16,
                 aug_list=None, label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        self.max_objects = max_objects
        self._det_augs = aug_list
        super().__init__(batch_size, data_shape, label_width=5,
                         aug_list=[], label_name=label_name, **kwargs)

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.max_objects, 5),
                         np.float32)]

    @staticmethod
    def _parse_label(raw):
        raw = np.asarray(raw, np.float32).ravel()
        # positive detection of the headed format [A, B, header..., objs]:
        # A = header width (>=2), B = object width (>=5).  A flat k*5 list
        # can't masquerade as headed: its second value is a normalized x1
        # in [0, 1], so int(raw[1]) < 5 there.
        if raw.size >= 2:
            a, b = int(raw[0]), int(raw[1])
            if a >= 2 and b >= 5 and raw.size > a \
                    and (raw.size - a) % b == 0:
                boxes = raw[a:].reshape(-1, b)[:, :5]
                # the headed heuristic can false-positive on a flat k*5
                # list with unnormalized pixel coords (x1 >= 5); headed
                # labels carry normalized coords, so when BOTH parses are
                # shape-possible and the headed coords fall outside
                # [0, 1], refuse rather than return corrupted boxes
                coords = boxes[:, 1:]
                ambiguous = raw.size % 5 == 0
                if ambiguous and coords.size and (
                        coords.min() < -1e-3 or coords.max() > 1 + 1e-3):
                    raise MXNetError(
                        "detection label matches the headed [A, B, ...] "
                        "pattern but parsed coordinates fall outside "
                        "[0, 1] — if this is a flat k*5 label, normalize "
                        "the box coordinates to [0, 1]")
                return boxes
        if raw.size % 5 != 0:
            raise MXNetError(
                "detection label of length %d is neither flat k*5 nor "
                "headed [A, B, ...]" % raw.size)
        return raw.reshape(-1, 5)

    def _load_one(self, key):
        import mxnet_tpu.recordio as recordio

        if self.record is not None:
            with self._rec_lock:
                raw = self.record.read_idx(key)
            header, img = recordio.unpack_img(raw)
            label = header.label
        else:
            label, fname = self.imglist[key]
            from .image import imread

            img = imread(fname)
        boxes = self._parse_label(label)
        # det augmenters index src.shape[2] / assume HWC; normalize a
        # grayscale decode to a 1-channel HWC array BEFORE the chain
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        for aug in self._det_augs:
            img, boxes = aug(img, boxes)
        img = np.asarray(img, np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        c, h, w = self.data_shape
        if img.shape[2] == 1 and c > 1:
            img = np.repeat(img, c, axis=2)
        if img.shape[:2] != (h, w):
            img = imresize(img.astype(np.uint8), w, h)
            img = np.asarray(img, np.float32).reshape(h, w, c)
        padded = np.full((self.max_objects, 5), -1.0, np.float32)
        n = min(len(boxes), self.max_objects)
        if n:
            padded[:n] = boxes[:n]
        return img.transpose(2, 0, 1), padded

    def next(self):
        batch = super().next()
        # parent stacked the (max_objects, 5) labels already; just make
        # sure the declared shape holds
        lab = batch.label[0]
        if lab.shape != (self.batch_size, self.max_objects, 5):
            from .ndarray import array

            batch = DataBatch(
                data=batch.data,
                label=[array(np.asarray(
                    lab.asnumpy()).reshape(
                    self.batch_size, self.max_objects, 5))],
                pad=batch.pad, index=batch.index,
                provide_data=self.provide_data,
                provide_label=self.provide_label)
        return batch
