"""Weight initializers (reference ``python/mxnet/initializer.py``).

The reference dispatches by name pattern (``_weight``/``_bias``/``_gamma``…)
via ``InitDesc`` and ``__init__`` attrs; semantics preserved here.
"""
from __future__ import annotations

import math
import re

import numpy as np

from .base import MXNetError, _Registry
from . import random as _random
from .ndarray import array, zeros as nd_zeros

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Constant",
           "Zero", "One", "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear",
           "LSTMBias", "Load", "Mixed", "register", "create"]

_registry = _Registry("initializer")


def _param_rng(desc):
    """Numpy stream for one parameter: a pure function of
    (``mx.random`` seed, parameter name), so init values replay
    bit-exactly regardless of init order or process count — the
    fold_in contract (docs/static_analysis.md, MX003)."""
    import zlib

    h = zlib.crc32(str(desc).encode("utf-8"))
    return np.random.RandomState(
        (_random.current_seed() * 1000003 + h) % (2 ** 31))


def register(klass):
    _registry.register(klass.__name__.lower(), klass)
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, (list, tuple)):
        # already-decoded dumps() form (Symbol.tojson round-trips the
        # attr through json, so it arrives as ['Name', {kwargs}])
        return _registry.get(str(name[0]).lower())(**(name[1] or {}))
    name = str(name)
    if name.startswith("["):
        # serialized form from Initializer.dumps(): '["name", {kwargs}]'
        # (the reference stores this json in the variable's __init__ attr)
        import json

        decoded = json.loads(name)
        return _registry.get(decoded[0].lower())(**decoded[1])
    return _registry.get(name.lower())(**kwargs)


class InitDesc(str):
    """Name + attrs descriptor (reference ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Dispatch on parameter name suffix, like the reference
    ``Initializer.__call__``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        """Serialized '["name", {kwargs}]' form (reference
        ``Initializer.dumps``); round-trips through :func:`create`."""
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("parameters"):
            # packed fused-RNN parameter blob (reference init.FusedRNN
            # unpacks per-matrix; here one small-uniform draw — same
            # divergence FusedRNNCell documents)
            self._init_rnn_parameters(desc, arr)
        elif name.endswith("state") or name.endswith("state_cell"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_rnn_parameters(self, desc, arr):
        arr[:] = _param_rng(desc).uniform(-0.07, 0.07,
                                          arr.shape).astype("float32")

    def _init_bilinear(self, _, arr):
        weight = np.zeros(arr.size, dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.)
        c = (2 * f - 1 - f % 2) / (2. * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i / shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_weight(self, desc, arr):
        raise NotImplementedError("virtual")

    def _init_default(self, desc, arr):
        raise MXNetError(
            "Unknown initialization pattern for %s" % desc)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        arr[:] = _param_rng(desc).uniform(-self.scale, self.scale,
                                          arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        arr[:] = _param_rng(desc).normal(0, self.sigma, arr.shape)


@register
class Constant(Initializer):
    def __init__(self, value=0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Zero(Constant):
    def __init__(self):
        super().__init__(0)


@register
class One(Constant):
    def __init__(self):
        super().__init__(1)


_registry.register("zeros", Zero)
_registry.register("ones", One)


@register
class Xavier(Initializer):
    """Glorot init (reference Xavier: rnd_type, factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.
        if len(shape) < 2:
            raise MXNetError(
                "Xavier initializer cannot be applied to vector %s" % desc)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        rng = _param_rng(desc)
        if self.rnd_type == "uniform":
            arr[:] = rng.uniform(-scale, scale, arr.shape)
        elif self.rnd_type == "gaussian":
            arr[:] = rng.normal(0, scale, arr.shape)
        else:
            raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming init (reference MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2. / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        rng = _param_rng(desc)
        if self.rand_type == "uniform":
            tmp = rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = self.scale * q.reshape(arr.shape)


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        Initializer._init_bilinear(self, _, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_bias = _init_weight


class Load:
    """Initialize from a dict of loaded arrays (reference Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            (k[4:] if k.startswith(("arg:", "aux:")) else k): v
            for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise MXNetError(
                    "Parameter %s has wrong shape %s vs %s"
                    % (name, arr.shape, self.param[name].shape))
            self.param[name].copyto(arr)
        else:
            if self.default_init is None:
                raise MXNetError("Cannot init %s: not in loaded param and no "
                                 "default init" % name)
            self.default_init(name, arr)


class Mixed:
    """Pattern-routed initializers (reference Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Parameter name %s did not match any pattern" % name)
