"""Data iterators.

Reference: ``python/mxnet/io.py`` (DataIter ABC, NDArrayIter, ResizeIter,
PrefetchingIter, MXDataIter) over the C++ iterator chain in ``src/io/``
(SURVEY.md §3.5).  The TPU build keeps the iterator-chain design —
source → batcher → background prefetcher — with the prefetcher as a Python
thread double-buffering host→device transfers (the role of
``PrefetcherIter``/``dmlc::ThreadedIter``).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import namedtuple

import numpy as np

from .base import MXNetError, get_env
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "DevicePrefetchIter", "prefetch_to_device",
           "CSVIter", "MNISTIter", "ImageRecordIter",
           "LibSVMIter", "ImageDetRecordIter",
           "DataServiceIter", "fold_in", "epoch_permutation"]


def _queue_get_or_die(q, thread, what, poll_s=0.2):
    """``queue.get`` that survives worker death.

    A plain blocking ``get`` deadlocks the consumer forever when the
    worker thread died without enqueueing its end-of-data sentinel (hard
    crash, injected kill, interpreter teardown race).  Poll instead:
    whenever the queue stays empty, check the worker is still alive and
    raise a diagnosable :class:`MXNetError` the moment it is not (after
    one final non-blocking drain to close the put-then-exit race)."""
    while True:
        try:
            return q.get(timeout=poll_s)
        except queue.Empty:
            if thread is None or not thread.is_alive():
                try:
                    return q.get_nowait()
                except queue.Empty:
                    raise MXNetError(
                        "%s worker thread died without delivering a "
                        "batch, an error, or end-of-data; the input "
                        "pipeline is broken (worker crashed or was "
                        "killed)" % what) from None


def _fault_hook(site, out_queue, stop_event):
    """Run the fault-injection hook for a worker loop.  Returns True when
    the worker must die *silently* (injected ``kill`` — no sentinel, no
    error: the consumer-side dead-worker detection is what's under
    test); a ``raise`` fault is forwarded through the queue like any
    organic worker error."""
    from .testing import faults

    try:
        faults.inject(site)
    except faults.WorkerKilled:
        return True
    except Exception as exc:
        if not stop_event.is_set():
            out_queue.put(exc)
        return True
    return False


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Data description (reference ``DataDesc``: name, shape, dtype, layout)."""

    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), np.dtype(dtype), layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """One batch (reference ``DataBatch``: data/label lists + pad/index)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator ABC (reference ``io.py:175``)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError

    # -- seekable protocol (O(1) resume) --------------------------------
    def seekable(self):
        """True when :meth:`seek` can jump this iterator to an absolute
        ``(epoch, nbatch)`` position without replaying batches — the O(1)
        resume path ``fit(resume_from=...)`` prefers over O(steps)
        replay.  Seekability requires the stream to be a pure function of
        position (deterministic or seeded shuffle)."""
        return False

    def seek(self, epoch, nbatch):
        """Position the stream so the next batch drawn is batch ``nbatch``
        of epoch ``epoch`` (both 0-based), exactly as if ``epoch`` resets
        and ``nbatch`` draws had been replayed."""
        raise MXNetError(
            "%s is not seekable (unseeded shuffle makes the stream a "
            "function of RNG history, not position); resume falls back "
            "to O(steps) replay" % type(self).__name__)


def _init_data(data, allow_empty, default_name):
    """Normalize data/label inputs to a list of (name, array) (reference
    ``io.py`` ``_init_data``)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    return [(k, v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
            for k, v in data.items()]


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad semantics
    (reference ``NDArrayIter``, ``io.py:514``)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.idx = np.arange(self.num_data)
        self.shuffle = shuffle
        # a private RNG makes the shuffle sequence a pure function of
        # (seed, reset count) — required for exact replay by
        # ``fit(resume_from=...)``, which fast-forwards by replaying
        # resets (the global np.random stream also feeds initializers,
        # so its draw position differs between cold start and resume)
        self._rng = np.random.RandomState(seed) if seed is not None \
            else np.random
        self._seed = seed
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.shuffle:
            self._rng.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def seekable(self):
        return (not self.shuffle) or self._seed is not None

    def seek(self, epoch, nbatch):
        """O(1)-in-steps jump: rebuild the private shuffle RNG at its
        epoch-``epoch`` state (one in-place shuffle per epoch boundary,
        exactly the draws replayed resets would make — the constructor's
        reset is shuffle #1 for epoch 0) and place the cursor directly;
        no batches are drawn."""
        if not self.seekable():
            raise MXNetError(
                "NDArrayIter with shuffle=True but no seed= is not "
                "seekable: the shuffle order is a function of global RNG "
                "history, not of (epoch, nbatch)")
        epoch, nbatch = int(epoch), int(nbatch)
        if self.shuffle:
            self.idx = np.arange(self.idx.shape[0])
            rng = np.random.RandomState(self._seed)
            for _ in range(epoch + 1):
                rng.shuffle(self.idx)
            self._rng = rng
        self.cursor = nbatch * self.batch_size - self.batch_size

    def _getdata(self, data_source):
        assert self.cursor < self.num_data
        sel = self.idx[self.cursor:self.cursor + self.batch_size]
        if len(sel) < self.batch_size:  # pad: wrap around
            pad = self.batch_size - len(sel)
            sel = np.concatenate([sel, self.idx[:pad]])
        return [array(x[1][sel]) for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch (reference
    ``ResizeIter``)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _ThreadedPrefetchTeardown(object):
    """Shared drain/stop/join teardown for the queue+thread prefetchers
    (:class:`PrefetchingIter`, :class:`DevicePrefetchIter`) — a dead- or
    wedged-worker fix lands once here, not per class."""

    def _drain(self, capture_error=False):
        """Empty the queue; with ``capture_error`` return the first
        pending worker exception found (an error the consumer never got
        to see), else None."""
        pending = None
        try:
            while True:
                item = self._queue.get_nowait()
                if capture_error and pending is None and \
                        isinstance(item, Exception):
                    pending = item
        except queue.Empty:
            pass
        return pending

    def close(self, timeout=5):
        """Stop the worker WITHOUT restarting it (``reset`` is
        stop-then-restart): signal stop, drain so a worker blocked on
        the full queue can exit, join with ``timeout``, and RE-RAISE any
        worker exception still pending in the queue — an error the
        consumer never observed must not vanish on teardown.  After
        ``close`` the iterator reports exhaustion until ``reset``; any
        inner iterators are left untouched for the caller to reuse."""
        self._stop.set()
        pending = self._drain(capture_error=True)
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                import logging

                logging.warning("%s worker did not exit within %ss on "
                                "close()", type(self).__name__, timeout)
            self._thread = None
        pending = pending or self._drain(capture_error=True)
        self._exhausted = True
        if pending is not None and pending is not self._worker_error:
            self._worker_error = pending
            raise pending

    def _halt(self):
        """Stop the worker WITHOUT restarting it and clear queue/error
        state — the shared first half of ``reset()`` and ``seek()``.
        Drain so a worker blocked on a full queue can observe the stop
        and exit; it may still enqueue the batch it was holding, so
        drain again AFTER the join so no stale batch survives into the
        restarted stream."""
        self._stop.set()
        self._drain()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._drain()
        self._worker_error = None
        self._exhausted = False

    def seekable(self):
        return all(getattr(i, "seekable", lambda: False)()
                   for i in self.iters)

    def seek(self, epoch, nbatch):
        """Jump the whole pipeline: halt the staging worker, seek every
        inner iterator to ``(epoch, nbatch)``, restart streaming from
        the new position.  ``nbatch`` counts raw inner batches (the
        units ``fit`` checkpoints), independent of any pack factor."""
        if not self.seekable():
            raise MXNetError(
                "%s cannot seek: inner iterator(s) %s are not seekable"
                % (type(self).__name__,
                   [type(i).__name__ for i in self.iters]))
        self._halt()
        for i in self.iters:
            i.seek(epoch, nbatch)
        self._start()

    def __del__(self):
        self._stop.set()


class PrefetchingIter(_ThreadedPrefetchTeardown, DataIter):
    """Background-thread prefetcher over one or more iterators (reference
    ``PrefetchingIter``, ``io.py:341`` ≈ ``PrefetcherIter``/
    ``dmlc::ThreadedIter`` in C++).  Overlaps host batch prep with device
    compute — the double-buffered input pipeline the TPU step needs."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        iters = iters if isinstance(iters, list) else [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self.current_batch = None
        self._worker_error = None
        self._exhausted = False
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _worker(self):
        while not self._stop.is_set():
            if _fault_hook("prefetch", self._queue, self._stop):
                return
            try:
                batches = [i.next() for i in self.iters]
            except StopIteration:
                self._queue.put(None)
                return
            except Exception as exc:  # surface at next() like ThreadedIter
                if not self._stop.is_set():
                    self._queue.put(exc)
                return
            self._queue.put(batches)

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._halt()
        for i in self.iters:
            i.reset()
        self._start()

    def iter_next(self):
        if self._worker_error is not None:
            # the worker died on this error; keep surfacing it (a fresh
            # reset() restarts the stream) instead of hanging on the
            # empty queue
            raise self._worker_error
        if self._exhausted:
            return False
        try:
            batches = _queue_get_or_die(self._queue, self._thread,
                                        type(self).__name__)
        except MXNetError as e:
            self._worker_error = e  # dead worker: fail every later call
            raise
        if batches is None:
            self._exhausted = True
            return False
        if isinstance(batches, Exception):
            self._worker_error = batches
            raise batches
        self.current_batch = DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([(b.label or []) for b in batches], []),
            pad=batches[0].pad, index=batches[0].index)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DevicePrefetchIter(_ThreadedPrefetchTeardown, DataIter):
    """Async *device*-staging prefetcher: the second pipeline stage on top
    of :class:`PrefetchingIter`'s host double-buffer.

    A background thread pulls host batches from the inner iterator(s),
    issues the host→device transfer (``jax.device_put``) into a ring of
    ``prefetch_depth`` (≥2) in-flight device buffers, and *waits for the
    copy on the staging thread* — so by the time ``Module.fit`` asks for
    batch N+1, its bytes are already resident and the consumer thread
    never blocks on the link.  This is what closes the fit-vs-step gap on
    hosts where a fresh-buffer ``device_put`` is slow (the repo measured
    3.6 MB/s over the tunneled link — ~9 s per 77 MB batch if paid
    synchronously in the step loop).

    Sharding-aware: under a ``mesh`` the batch is placed with the proper
    batch ``NamedSharding`` up front (``parallel.sharding.shard_batch``),
    so DP/FSDP meshes consume pre-sharded arrays with no re-layout in the
    fused step.  Without a mesh, batches land on ``context``'s device (or
    the default device).

    ``steps_per_call=K`` packs K consecutive batches into one super-batch
    with a leading K axis — one transfer and one dispatch feed K
    ``lax.scan``'d updates (:class:`~mxnet_tpu.fused.TrainStep` with
    ``steps_per_call=K``).  The trailing ``len(epoch) % K`` batches of an
    epoch are dropped (a partial pack would recompile the scanned step);
    ``provide_data``/``provide_label`` keep the *per-step* shapes.

    Emitted batches carry ``staged=True`` so consumers skip their own
    placement pass.
    """

    def __init__(self, iters, prefetch_depth=2, mesh=None, context=None,
                 steps_per_call=1):
        iters = iters if isinstance(iters, list) else [iters]
        super().__init__(iters[0].batch_size)
        if prefetch_depth < 1:
            raise MXNetError("prefetch_depth must be >= 1")
        if steps_per_call < 1:
            raise MXNetError("steps_per_call must be >= 1")
        self.iters = iters
        self.mesh = mesh
        self.context = context
        self._pack = int(steps_per_call)
        self._queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self.current_batch = None
        self._worker_error = None
        self._warned_drop = False
        self._exhausted = False
        # consumer-side staging-wait accounting: how long next() blocked
        # on the ring vs how many batches it delivered.  When the ratio
        # is high the pipeline is INPUT-bound (decode/transfer cannot
        # keep up with the device); bench_fit.py reports the attribution
        self.stage_wait_s = 0.0
        self.batches_delivered = 0
        self._start()

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])

    # -- staging --------------------------------------------------------
    def _placement(self):
        """(fn: host/np/jax array -> committed device array) resolved
        lazily so constructing the iterator never initializes a backend
        the process does not use."""
        import jax

        if self.mesh is not None:
            from .parallel.sharding import shard_batch

            leading = 1 if self._pack > 1 else 0
            return lambda v: shard_batch(self.mesh, v, leading=leading)
        if self.context is not None:
            dev = self.context.jax_device
        else:
            dev = jax.local_devices()[0]
        return lambda v: jax.device_put(v, dev)

    @staticmethod
    def _host_array(arr):
        if isinstance(arr, NDArray):
            return np.asarray(arr._data)
        return np.asarray(arr)

    def _stage_group(self, group):
        """group: list (length pack) of per-iter batch lists -> one staged
        DataBatch.  Runs on the worker thread: the device_put AND the wait
        for transfer completion both happen here, off the consumer."""
        import jax

        place = self._placement()
        first = group[0]
        n_data = [len(b.data) for b in first]
        n_label = [len(b.label or []) for b in first]

        def stage_slot(get_arrays, counts):
            staged = []
            for it_idx, n in enumerate(counts):
                for j in range(n):
                    if self._pack == 1:
                        arr = get_arrays(group[0][it_idx])[j]
                        v = arr._data if isinstance(arr, NDArray) \
                            else np.asarray(arr)
                    else:
                        v = np.stack([
                            self._host_array(get_arrays(g[it_idx])[j])
                            for g in group])
                    out = place(v)
                    ctx = self.context
                    staged.append(NDArray(out, ctx) if ctx is not None
                                  else NDArray(out))
            return staged

        data = stage_slot(lambda b: b.data, n_data)
        label = stage_slot(lambda b: b.label or [], n_label)
        # eat the h2d latency HERE so the consumer never does
        jax.block_until_ready([a._data for a in data + label])
        batch = DataBatch(data=data, label=label,
                          pad=first[0].pad if self._pack == 1 else 0,
                          index=first[0].index if self._pack == 1 else None,
                          bucket_key=first[0].bucket_key,
                          provide_data=first[0].provide_data,
                          provide_label=first[0].provide_label)
        batch.staged = True
        return batch

    # -- worker ---------------------------------------------------------
    def _worker(self):
        while not self._stop.is_set():
            if _fault_hook("device_prefetch", self._queue, self._stop):
                return
            group = []
            try:
                for _ in range(self._pack):
                    group.append([i.next() for i in self.iters])
            except StopIteration:
                if group and not self._warned_drop:
                    self._warned_drop = True
                    import logging

                    logging.warning(
                        "DevicePrefetchIter(steps_per_call=%d): dropping "
                        "%d trailing batch(es) that do not fill a pack",
                        self._pack, len(group))
                self._queue.put(None)
                return
            except Exception as exc:  # surface at next() like ThreadedIter
                if not self._stop.is_set():
                    self._queue.put(exc)
                return
            try:
                staged = self._stage_group(group)
            except Exception as exc:
                if not self._stop.is_set():
                    self._queue.put(exc)
                return
            self._queue.put(staged)

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._halt()
        for i in self.iters:
            i.reset()
        self._start()

    def reset_stage_stats(self):
        self.stage_wait_s = 0.0
        self.batches_delivered = 0

    def iter_next(self):
        if self._worker_error is not None:
            # worker died on this error; keep surfacing it (reset()
            # restarts the stream) instead of hanging on an empty queue
            raise self._worker_error
        if self._exhausted:
            # keep returning False (the worker is gone — a fresh get()
            # would block forever); reset() restarts the stream
            return False
        t0 = time.perf_counter()
        try:
            batch = _queue_get_or_die(self._queue, self._thread,
                                      "DevicePrefetchIter")
        except MXNetError as e:
            self._worker_error = e  # dead worker: fail every later call
            raise
        if batch is None:
            self._exhausted = True
            return False
        if isinstance(batch, Exception):
            self._worker_error = batch
            raise batch
        self.stage_wait_s += time.perf_counter() - t0
        self.batches_delivered += 1
        self.current_batch = batch
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def prefetch_to_device(iters, prefetch_depth=2, mesh=None, context=None,
                       steps_per_call=1):
    """Wrap an iterator (or list of iterators) in a
    :class:`DevicePrefetchIter` — idempotent: an iterator that is already
    device-staging is returned as-is (same pack), so callers can apply it
    unconditionally."""
    if isinstance(iters, DevicePrefetchIter) and \
            iters._pack == steps_per_call:
        return iters
    return DevicePrefetchIter(iters, prefetch_depth=prefetch_depth,
                              mesh=mesh, context=context,
                              steps_per_call=steps_per_call)


class CSVIter(NDArrayIter):
    """CSV source (reference ``src/io/iter_csv.cc``; here parsed with
    numpy, feeding the same batching machinery)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype="float32")
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype="float32")
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label.ravel()
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class LibSVMIter(DataIter):
    """LibSVM-format source yielding CSR data batches (reference
    ``src/io/iter_libsvm.cc``): lines of ``label idx:val idx:val ...``.
    ``data_libsvm`` may also carry sparse labels (``label_libsvm`` for a
    separate label file).  Batches pad the tail like NDArrayIter
    (``batch.pad`` rows repeated from the front)."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, data_name="data",
                 label_name="softmax_label", part_index=0, num_parts=1,
                 **kwargs):
        super().__init__(batch_size)
        from .ndarray.sparse import csr_matrix

        self._data_name = data_name
        self._label_name = label_name
        ncol = int(data_shape[-1] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        vals, cols, indptr, labels = self._parse(data_libsvm, ncol)
        if label_libsvm is not None:
            lcol = int(label_shape[-1] if isinstance(
                label_shape, (tuple, list)) else (label_shape or 1))
            lv, lc, lp, _ = self._parse(label_libsvm, lcol)
            dense_lab = np.zeros((len(lp) - 1, lcol), "float32")
            for r in range(len(lp) - 1):
                dense_lab[r, lc[lp[r]:lp[r + 1]]] = lv[lp[r]:lp[r + 1]]
            labels = dense_lab.squeeze()
        n = len(indptr) - 1
        if num_parts > 1:  # sharded reading, same contract as the C iter
            per = n // num_parts
            lo, hi = part_index * per, (part_index + 1) * per \
                if part_index < num_parts - 1 else n
            sel = range(lo, hi)
            vals, cols, indptr, labels = self._take(vals, cols, indptr,
                                                    labels, sel)
            n = len(indptr) - 1
        self._vals, self._cols, self._indptr = vals, cols, indptr
        self._labels = np.asarray(labels, "float32")
        self._ncol = ncol
        self._num = n
        self._csr = csr_matrix
        self.reset()

    @staticmethod
    def _parse(path, ncol):
        vals, cols, indptr, labels = [], [], [0], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    cols.append(int(i))
                    vals.append(float(v))
                indptr.append(len(cols))
        return (np.asarray(vals, "float32"), np.asarray(cols, "int32"),
                np.asarray(indptr, "int64"), np.asarray(labels, "float32"))

    @staticmethod
    def _take(vals, cols, indptr, labels, rows):
        nv, nc, np_ = [], [], [0]
        for r in rows:
            nv.extend(vals[indptr[r]:indptr[r + 1]])
            nc.extend(cols[indptr[r]:indptr[r + 1]])
            np_.append(len(nc))
        return (np.asarray(nv, "float32"), np.asarray(nc, "int32"),
                np.asarray(np_, "int64"), labels[list(rows)])

    @property
    def provide_data(self):
        return [DataDesc(self._data_name, (self.batch_size, self._ncol))]

    @property
    def provide_label(self):
        lshape = (self.batch_size,) + tuple(self._labels.shape[1:])
        return [DataDesc(self._label_name, lshape)]

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor >= self._num:
            raise StopIteration
        rows = [(self._cursor + i) % self._num
                for i in range(self.batch_size)]
        pad = max(0, self._cursor + self.batch_size - self._num)
        vals, cols, indptr, labels = self._take(
            self._vals, self._cols, self._indptr, self._labels, rows)
        data = self._csr((vals, cols, indptr),
                         shape=(self.batch_size, self._ncol))
        from .ndarray import array

        self._cursor += self.batch_size
        return DataBatch(data=[data], label=[array(labels)], pad=pad)


class MNISTIter(NDArrayIter):
    """MNIST source (reference ``src/io/iter_mnist.cc``).  Reads the
    canonical idx-format files if present; raises otherwise (no network in
    the build environment)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, **kwargs):
        import gzip
        import os
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            if not os.path.exists(path) and os.path.exists(path + ".gz"):
                path, opener = path + ".gz", gzip.open
            with opener(path, "rb") as f:
                magic = struct.unpack(">I", f.read(4))[0]
                ndim = magic & 0xFF
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

        images = read_idx(image).astype("float32") / 255.0
        labels = read_idx(label).astype("float32")
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        super().__init__(images, labels, batch_size=batch_size,
                         shuffle=shuffle, **kwargs)


def ImageRecordIter(path_imgrec, data_shape, batch_size, path_imgidx=None,
                    label_width=1, shuffle=False, part_index=0, num_parts=1,
                    resize=0, rand_crop=False, rand_mirror=False,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=0.0, std_g=0.0, std_b=0.0,
                    max_random_contrast=0, max_random_illumination=0,
                    preprocess_threads=4, prefetch_buffer=2,
                    data_name="data", label_name="softmax_label",
                    num_workers=None, seed=None, **kwargs):
    """RecordIO-backed image iterator (reference C iterator
    ``ImageRecordIter``, ``src/io/iter_image_recordio_2.cc:513`` + the
    default augmenter chain ``src/io/image_aug_default.cc``).

    Factory with the C iterator's parameter surface.  Two backends:

    * ``num_workers > 0`` (or ``MXNET_DATA_WORKERS``): the sharded
      deterministic data service — a :class:`DataServiceIter` over a
      picklable :class:`~mxnet_tpu.image.RecordImageLoader` with a
      multiprocess decode pool, cross-host global shuffle from ``seed``
      (``rank::nproc`` striding via ``part_index``/``num_parts``), and
      O(1) ``seek`` resume.
    * otherwise the classic :class:`~mxnet_tpu.image.ImageIter` with the
      matching augmenter list (resize -> crop -> mirror -> jitter ->
      normalize), threaded decode, and contiguous
      ``part_index``/``num_parts`` sharding.

    Either backend is wrapped in :class:`PrefetchingIter` so host-side
    batch assembly overlaps device steps.
    """
    from . import image as img_mod

    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
    std = None
    if std_r or std_g or std_b:
        std = np.array([std_r or 1.0, std_g or 1.0, std_b or 1.0],
                       np.float32)
    aug_list = img_mod.CreateAugmenter(
        data_shape, resize=resize, rand_crop=rand_crop,
        rand_mirror=rand_mirror, mean=mean, std=std,
        contrast=max_random_contrast, brightness=max_random_illumination)
    workers = int(num_workers if num_workers is not None
                  else get_env("MXNET_DATA_WORKERS", 0, int))
    if workers > 0:
        from . import recordio as rec_mod
        from .image import RecordImageLoader

        idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
        record = rec_mod.MXIndexedRecordIO(idx_path, path_imgrec, "r")
        loader = RecordImageLoader(
            data_shape, record=record, aug_list=aug_list,
            label_width=label_width, data_name=data_name,
            label_name=label_name)
        svc = DataServiceIter(
            loader, batch_size, seed=seed, shuffle=shuffle,
            num_workers=workers, rank=part_index, nproc=num_parts)
        return PrefetchingIter(svc, prefetch_depth=prefetch_buffer)
    inner = img_mod.ImageIter(
        batch_size, data_shape, label_width=label_width,
        path_imgrec=path_imgrec, path_imgidx=path_imgidx, shuffle=shuffle,
        part_index=part_index, num_parts=num_parts, aug_list=aug_list,
        data_name=data_name, label_name=label_name,
        num_threads=preprocess_threads, seed=seed, **kwargs)
    return PrefetchingIter(inner, prefetch_depth=prefetch_buffer)


def ImageDetRecordIter(path_imgrec, data_shape, batch_size,
                       max_objects=16, preprocess_threads=4,
                       prefetch_buffer=2, **kwargs):
    """Detection RecordIO iterator (reference C iterator
    ``ImageDetRecordIter``, ``src/io/iter_image_det_recordio.cc``):
    factory over :class:`mxnet_tpu.image_detection.ImageDetIter` with the
    det augmenter chain, threaded decode, and background prefetch —
    same pipeline contract as :func:`ImageRecordIter`."""
    from .image_detection import CreateDetAugmenter, ImageDetIter

    aug_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                  if k in ("resize", "rand_crop", "rand_pad",
                           "rand_mirror", "mean", "std", "brightness",
                           "contrast", "saturation", "inter_method",
                           "min_object_covered", "aspect_ratio_range",
                           "area_range", "pad_val")}
    aug_list = CreateDetAugmenter(data_shape, **aug_kwargs)
    inner = ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec,
                         max_objects=max_objects, aug_list=aug_list,
                         num_threads=preprocess_threads, **kwargs)
    return PrefetchingIter(inner, prefetch_depth=prefetch_buffer)


# the data-service layer builds on the iterator ABC above; imported last
# to avoid a circular import, re-exported here so the data plane has one
# front door (``mxnet_tpu.io``)
from .data_service import (DataServiceIter, epoch_permutation,  # noqa: E402
                           fold_in)
