"""KVStore — parameter synchronization.

Reference: ``python/mxnet/kvstore.py`` over ``src/kvstore/`` (SURVEY.md
§2.1/§2.3): ``local``/``device`` do in-process reductions (``CommCPU``/
``CommDevice``), ``dist_sync``/``dist_async`` talk to ps-lite parameter
servers over ZMQ.

TPU-native re-design (the north star's ``dist_tpu_sync``): there are no
parameter servers.  A KVStore is keyed storage plus a *reduction domain*:

* ``local`` / ``device`` — single-process store; ``push`` sums gradient
  lists with one jitted tree-add (the reference's Comm tree-reduce
  collapses into an XLA fusion) and either applies the updater
  (``update_on_kvstore``) or stores the merged gradient for ``pull``.
* ``dist_tpu_sync`` / ``dist_sync`` / ``dist_device_sync`` — the same API
  running under SPMD: every host runs the same program, and cross-chip
  gradient summation is an XLA all-reduce over ICI inserted by the
  compiler when the train step is jitted over a ``jax.sharding.Mesh``
  (see ``mxnet_tpu.parallel``).  ``push`` therefore performs a
  ``jax.lax.psum``-backed reduction via ``parallel.allreduce`` when a mesh
  is active, and the updater runs identically on every replica — the
  TPU equivalent of "update on server, pull updated weights" with zero
  RPC.  ``rank``/``num_workers`` map to ``jax.process_index/count``.

The gradient-priority overlap the reference gets from
``priority=-param_index`` (``model.py:105``) comes for free: XLA schedules
collectives asynchronously inside the fused step and overlaps them with
remaining backward compute.
"""
from __future__ import annotations

import pickle

from .base import MXNetError
from .ndarray import NDArray, zeros, imperative_invoke

__all__ = ["KVStore", "create"]

_VALID_TYPES = ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "dist_sync", "dist_device_sync", "dist_async",
                "dist_tpu_sync", "dist")


def create(name="local"):
    """Create a KVStore (reference ``kvstore.create``,
    ``src/kvstore/kvstore.cc:34``)."""
    if not isinstance(name, str) or name not in _VALID_TYPES:
        raise MXNetError("Unknown KVStore type %r (valid: %s)"
                         % (name, ", ".join(_VALID_TYPES)))
    return KVStore(name)


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._merged = {}
        self._updater = None
        self._optimizer = None
        self._is_dist = "dist" in kv_type
        self._mesh = None
        if "async" in kv_type:
            # In the reference, dist_async servers apply each worker's
            # gradient immediately without a merge barrier
            # (kvstore_dist_server.h sync_mode_=false).  The SPMD design
            # has no servers and every replica steps in lockstep, so
            # async degenerates to synchronous updates.  This is a
            # documented alias, not silent: warn once.
            import logging

            logging.getLogger(__name__).warning(
                "kvstore %r: asynchronous server semantics do not exist "
                "under single-controller SPMD; updates are synchronous "
                "(equivalent to dist_tpu_sync)", kv_type)

    # -- identity -------------------------------------------------------
    @property
    def rank(self):
        import jax

        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        import jax

        return jax.process_count() if self._is_dist else 1

    # -- core API -------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0):
        """Reduce gradients into the store.

        ``value`` may be one NDArray or a per-device list (the reference's
        multi-GPU path); lists are tree-added in one fused XLA op.  Under a
        dist type with an active mesh, the merged gradient is all-reduced
        over the mesh data axis (ICI collective).  ``priority`` is accepted
        for API parity; XLA's scheduler owns collective ordering.
        """
        keys, values = self._normalize(key, value, allow_list=True)
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            merged = self._reduce(vs)
            if self._is_dist:
                merged = self._cross_replica_sum(merged)
            if self._updater is not None:
                self._updater(self._key_index(k), merged, self._store[k])
            else:
                self._merged[k] = merged

    def pull(self, key, out=None, priority=0):
        keys, outs = self._normalize(key, out, allow_list=True)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            src = self._store[k] if self._updater is not None or \
                k not in self._merged else self._merged[k]
            targets = os_ if isinstance(os_, (list, tuple)) else [os_]
            for tgt in targets:
                src.copyto(tgt)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (reference PullRowSparse).
        Dense store + gather keeps shapes static for XLA."""
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys, outs = self._normalize(key, out, allow_list=True)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, os_, rid in zip(keys, outs, rids):
            src = self._store[k]
            rows = imperative_invoke("take", [src, rid], {"axis": 0})[0]
            targets = os_ if isinstance(os_, (list, tuple)) else [os_]
            for tgt in targets:
                if tgt.shape == rows.shape:
                    rows.copyto(tgt)
                else:  # scatter rows back into a full-shape target
                    tgt[:] = 0.0
                    tgt._set_data(tgt._data.at[
                        rid._data.astype("int32")].set(rows._data))

    # -- optimizer plumbing --------------------------------------------
    def set_optimizer(self, optimizer):
        """Install the optimizer server-side (reference pickles it to the
        ps-lite servers via ``_send_command_to_servers``; here every
        replica runs it identically inside the same program)."""
        from . import optimizer as opt

        # round-trip through pickle to mirror the reference contract that
        # the optimizer must be serializable
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    @property
    def updater(self):
        return self._updater

    # -- barriers / control --------------------------------------------
    def barrier(self):
        """Global barrier (reference ``MXKVStoreBarrier``).  Under SPMD all
        replicas run in lockstep inside compiled steps; between steps we
        only need to drain local async work."""
        from .ndarray import waitall

        waitall()

    def _send_command_to_servers(self, head, body):
        pass  # no servers in the TPU design

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- internals ------------------------------------------------------
    @staticmethod
    def _normalize(key, value, allow_list=False):
        if isinstance(key, (str, int)):
            return [key], [value]
        assert len(key) == len(value)
        return list(key), list(value)

    @staticmethod
    def _key_index(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @staticmethod
    def _reduce(vs):
        if isinstance(vs, NDArray):
            return vs
        if len(vs) == 1:
            return vs[0]
        return imperative_invoke("add_n", list(vs), {})[0]

    def _cross_replica_sum(self, arr):
        """All-reduce across replicas: over the active mesh's data axis
        for per-chip partial gradients (ICI collective), over DCN for
        multi-process values; identity when the pushed gradient is
        already global (the fused SPMD step's case)."""
        from .parallel import collectives
        from .parallel.mesh import current_mesh

        mesh = getattr(self, "_mesh", None) or current_mesh()
        return collectives.allreduce_nd(arr, mesh=mesh)
