"""KVStore — parameter synchronization.

Reference: ``python/mxnet/kvstore.py`` over ``src/kvstore/`` (SURVEY.md
§2.1/§2.3): ``local``/``device`` do in-process reductions (``CommCPU``/
``CommDevice``), ``dist_sync``/``dist_async`` talk to ps-lite parameter
servers over ZMQ.

TPU-native re-design (the north star's ``dist_tpu_sync``): there are no
parameter servers.  A KVStore is keyed storage plus a *reduction domain*:

* ``local`` / ``device`` — single-process store; ``push`` sums gradient
  lists with one jitted tree-add (the reference's Comm tree-reduce
  collapses into an XLA fusion) and either applies the updater
  (``update_on_kvstore``) or stores the merged gradient for ``pull``.
* ``dist_tpu_sync`` / ``dist_sync`` / ``dist_device_sync`` — the same API
  running under SPMD: every host runs the same program, and cross-chip
  gradient summation is an XLA all-reduce over ICI inserted by the
  compiler when the train step is jitted over a ``jax.sharding.Mesh``
  (see ``mxnet_tpu.parallel``).  ``push`` therefore performs a
  ``jax.lax.psum``-backed reduction via ``parallel.allreduce`` when a mesh
  is active, and the updater runs identically on every replica — the
  TPU equivalent of "update on server, pull updated weights" with zero
  RPC.  ``rank``/``num_workers`` map to ``jax.process_index/count``.

The gradient-priority overlap the reference gets from
``priority=-param_index`` (``model.py:105``) comes for free: XLA schedules
collectives asynchronously inside the fused step and overlaps them with
remaining backward compute.
"""
from __future__ import annotations

import os
import pickle

from .base import MXNetError, get_env, logger
from .ndarray import NDArray, zeros, imperative_invoke

__all__ = ["KVStore", "create"]


def _retry_backoffs(rank, base_s, attempts, cap_s=30.0):
    """Per-rank decorrelated-jitter retry schedule.

    Plain exponential backoff is synchronized: every rank that hit the
    same rendezvous race sleeps the same 1s/2s/4s and the whole job
    re-collides (thundering herd) on each retry.  Decorrelated jitter
    (AWS architecture blog) breaks the lockstep — ``sleep = min(cap,
    uniform(base, prev * 3))`` — and seeding the stream from the rank
    makes each rank's schedule *different from its peers yet
    reproducible run-over-run*, so a flaky-rendezvous repro retries on
    the exact same schedule every time."""
    import hashlib
    import random

    digest = hashlib.sha256(b"kv-backoff-%d" % int(rank)).digest()
    rng = random.Random(int.from_bytes(digest[:8], "big"))
    schedule, prev = [], float(base_s)
    for _ in range(int(attempts)):
        prev = min(float(cap_s), rng.uniform(float(base_s), prev * 3.0))
        schedule.append(prev)
    return schedule


def _run_bounded(fn, what, timeout_s=None, retries=0, backoff_s=1.0,
                 diagnose=None):
    """Run ``fn()`` under a wall-clock bound with retry/backoff.

    The DCN rendezvous and collectives block inside C calls with no
    native timeout: one wedged or dead peer deadlocks every healthy rank
    forever.  ``fn`` therefore runs on a helper thread; if it has not
    finished within ``timeout_s`` (``MXNET_KV_TIMEOUT_S``, 0 disables
    the bound) a diagnosable :class:`MXNetError` names the wedged site
    instead.  Transient non-MXNetError failures are retried up to
    ``retries`` times (``MXNET_KV_RETRIES``) on a rank-seeded
    decorrelated-jitter schedule (:func:`_retry_backoffs`) —
    rendezvous races at job start are the common case, and jitter keeps
    the retrying ranks from re-colliding in lockstep.  The abandoned
    helper thread cannot be killed; it is left daemonized (the process
    is about to fail loudly anyway, which is the point).

    ``diagnose``: optional zero-arg callable returning extra text for
    the timeout error — the heartbeat wiring uses it so the survivor
    NAMES the dead/stale peer instead of timing out anonymously."""
    import threading
    import time

    if timeout_s is None:
        timeout_s = get_env("MXNET_KV_TIMEOUT_S", 300.0, float)
    attempt = 0
    backoffs = _retry_backoffs(get_env("MXNET_WORKER_ID", 0, int),
                               backoff_s, retries) if retries else []
    while True:
        box = {}

        def _call():
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — forwarded below
                box["error"] = e

        t = threading.Thread(target=_call, daemon=True,
                             name="kv-bounded:%s" % what)
        t.start()
        t.join(timeout=timeout_s if timeout_s and timeout_s > 0 else None)
        if t.is_alive():
            extra = ""
            if diagnose is not None:
                try:
                    extra = diagnose() or ""
                except Exception as e:  # diagnosis must not mask the timeout
                    extra = "; peer diagnosis failed: %s" % e
            raise MXNetError(
                "%s did not complete within %.0fs (MXNET_KV_TIMEOUT_S); "
                "a peer process is likely wedged, dead, or unreachable — "
                "check every worker's log before restarting the job%s"
                % (what, timeout_s, extra))
        if "error" not in box:
            return box.get("value")
        err = box["error"]
        if attempt >= retries or isinstance(
                err, (MXNetError, KeyboardInterrupt, SystemExit)):
            if isinstance(err, MXNetError):
                raise err
            raise MXNetError("%s failed after %d attempt(s): %s"
                             % (what, attempt + 1, err)) from err
        attempt += 1
        sleep_s = backoffs[attempt - 1]
        logger.warning("%s failed (%s); retry %d/%d in %.2fs "
                       "(rank-seeded decorrelated jitter)",
                       what, err, attempt, retries, sleep_s)
        time.sleep(sleep_s)

_VALID_TYPES = ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "dist_sync", "dist_device_sync", "dist_async",
                "dist_tpu_sync", "dist")


def create(name="local"):
    """Create a KVStore (reference ``kvstore.create``,
    ``src/kvstore/kvstore.cc:34``)."""
    if not isinstance(name, str) or name not in _VALID_TYPES:
        raise MXNetError("Unknown KVStore type %r (valid: %s)"
                         % (name, ", ".join(_VALID_TYPES)))
    return KVStore(name)


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._merged = {}
        self._updater = None
        self._optimizer = None
        self._is_dist = "dist" in kv_type
        self._mesh = None
        if self._is_dist:
            # join the multi-process job when launched by tools/launch.py
            # (MXNET_COORDINATOR & co.); no-op single-process.  This is
            # what makes the documented quick-start actually synchronize
            # — without it each worker would silently train a separate
            # replica (jax.process_count() == 1 everywhere).  Bounded +
            # retried: rendezvous against a coordinator that is still
            # starting is the normal cold-start race, and rendezvous
            # against one that never comes up must fail loudly, not
            # hang the worker forever.
            from .parallel import init_distributed

            _run_bounded(init_distributed,
                         "KVStore %r init (jax.distributed rendezvous)"
                         % kv_type,
                         retries=get_env("MXNET_KV_RETRIES", 2, int))
            # liveness beacons: each rank rewrites a heartbeat file
            # under MXNET_HEARTBEAT_DIR so a survivor of a timed-out
            # collective can NAME the dead peer (no-op unconfigured)
            from .health import RankHeartbeat

            self._heartbeat = RankHeartbeat.maybe_start(
                self.rank, self.num_workers)
        self._is_async = "async" in kv_type
        if self._is_async:
            # The reference's dist_async servers apply each worker's
            # gradient immediately, no merge barrier
            # (kvstore_dist_server.h sync_mode_=false at :226).  The
            # TPU-native equivalent of "workers progress without
            # per-step coordination" is bounded-staleness LOCAL
            # updates: each host applies its own gradients immediately
            # (sync over ICI within its slice, zero DCN traffic per
            # step) and hosts meet only at parameter-AVERAGING rounds —
            # every epoch, plus every MXNET_ASYNC_SYNC_PERIOD local
            # updates when set (>0 requires all hosts to run the same
            # number of steps per epoch, since averaging is a
            # collective).  Staleness is bounded by the averaging
            # window; see docs/distributed.md.
            self._async_period = get_env("MXNET_ASYNC_SYNC_PERIOD", 0,
                                         int)
            self._async_steps = 0

    # -- identity -------------------------------------------------------
    @property
    def rank(self):
        import jax

        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        import jax

        return jax.process_count() if self._is_dist else 1

    # -- core API -------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0, is_partial_stack=False):
        """Reduce gradients into the store.

        ``value`` may be one NDArray or a per-device list (the reference's
        multi-GPU path); lists are tree-added in one fused XLA op.  Under a
        dist type with an active mesh, the merged gradient is all-reduced
        over the mesh data axis (ICI collective).  A caller holding
        per-chip partials stacked on a leading device axis must pass
        ``is_partial_stack=True``.  ``priority`` is accepted for API
        parity; XLA's scheduler owns collective ordering.
        """
        from .ndarray.sparse import BaseSparseNDArray

        keys, values = self._normalize(key, value, allow_list=True)
        merged_list = []
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            merged_list.append(self._reduce(vs))
        if self._is_dist and not self._is_async:
            import jax

            multi = jax.process_count() > 1
            dense_idx = [i for i, m in enumerate(merged_list)
                         if not isinstance(m, BaseSparseNDArray)]
            from .base import get_env

            batched = set()
            if multi and len(dense_idx) > 1 and not is_partial_stack \
                    and get_env("MXNET_KVSTORE_BATCH_PUSH", 1, int):
                # batched DCN reduce: ONE flattened allgather round trip
                # for the whole key list instead of one per key — the
                # comm-hygiene analogue of the reference's priority
                # batching (callers push keys in priority order,
                # model.py:105-116)
                dense = [merged_list[i] for i in dense_idx]
                reduced = self._bounded_collective(
                    lambda: self._cross_replica_sum_flat(dense),
                    "KVStore batched cross-replica gradient sum")
                for i, m in zip(dense_idx, reduced):
                    merged_list[i] = m
                batched = set(dense_idx)
            for i, merged in enumerate(merged_list):
                if i in batched:
                    continue
                if isinstance(merged, BaseSparseNDArray):
                    if multi:
                        from .ndarray.sparse import (RowSparseNDArray,
                                                     cast_storage)

                        if isinstance(merged, RowSparseNDArray):
                            # stays sparse on the wire: padded-nnz
                            # allgather + sparse merge (the bandwidth
                            # win row_sparse exists for; reference
                            # kvstore_dist.h:346-385)
                            from .parallel.collectives import \
                                allreduce_row_sparse

                            merged_list[i] = allreduce_row_sparse(merged)
                        else:  # CSR: densify (no CSR wire format yet)
                            stype = merged.stype
                            dense = self._cross_replica_sum(
                                merged.todense(),
                                is_partial_stack=is_partial_stack)
                            merged_list[i] = cast_storage(dense, stype)
                else:
                    merged_list[i] = self._cross_replica_sum(
                        merged, is_partial_stack=is_partial_stack)
        for k, merged in zip(keys, merged_list):
            if self._updater is not None:
                self._updater(self._key_index(k), merged, self._store[k])
            else:
                self._merged[k] = merged

    def pull(self, key, out=None, priority=0):
        keys, outs = self._normalize(key, out, allow_list=True)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            src = self._store[k] if self._updater is not None or \
                k not in self._merged else self._merged[k]
            targets = os_ if isinstance(os_, (list, tuple)) else [os_]
            for tgt in targets:
                src.copyto(tgt)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (reference ``PullRowSparse``,
        ``src/kvstore/kvstore_dist.h:346-385``).  The store keeps weights
        dense; requested rows are gathered with static shapes.  ``out``
        may be a RowSparseNDArray (filled with deduped sorted rows — the
        reference's unique-keys contract) or a dense NDArray."""
        import numpy as np

        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray

        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys, outs = self._normalize(key, out, allow_list=True)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        import jax.numpy as jnp

        for k, os_, rid in zip(keys, outs, rids):
            # same source selection as pull(): without an updater the
            # merged gradient is the pullable value
            src = self._store[k] if self._updater is not None or \
                k not in self._merged else self._merged[k]
            orig_ids = np.asarray(
                rid.asnumpy() if isinstance(rid, NDArray) else rid
            ).astype("int32")

            def gather(idx_np):
                idx = jnp.asarray(idx_np, "int32")
                from .ndarray.sparse import RowSparseNDArray as _RSP

                if isinstance(src, _RSP):
                    # lookup logical rows in sorted sparse storage
                    nnz = src._data.shape[0]
                    if nnz == 0:
                        return jnp.zeros((len(idx_np),) + src.shape[1:],
                                         src._data.dtype)
                    pos = jnp.clip(jnp.searchsorted(src._indices, idx),
                                   0, nnz - 1)
                    found = src._indices[pos] == idx
                    rows = src._data[pos]
                    return jnp.where(
                        found.reshape((-1,) + (1,) * (rows.ndim - 1)),
                        rows, 0)
                base = src.todense() if isinstance(
                    src, BaseSparseNDArray) else src
                return base._data[idx]

            targets = os_ if isinstance(os_, (list, tuple)) else [os_]
            for tgt in targets:
                if isinstance(tgt, RowSparseNDArray):
                    # deduped sorted rows (reference unique-keys
                    # contract); rebuilt through the constructor so the
                    # nnz-bucketing invariants hold without hand
                    # maintenance
                    uniq = np.unique(orig_ids)
                    fresh = RowSparseNDArray(
                        gather(uniq), jnp.asarray(uniq, "int32"),
                        tuple(src.shape), tgt.context)
                    tgt._indices = fresh._indices
                    tgt._sp_shape = fresh._sp_shape
                    tgt._true_nnz = fresh._true_nnz
                    tgt._set_data(fresh._data)
                elif tgt.shape == (len(orig_ids),) + tuple(src.shape[1:]):
                    # dense per-request rows, original order incl. dups
                    tgt._set_data(gather(orig_ids))
                elif tgt.shape == tuple(src.shape):
                    # full-shape target: scatter requested rows
                    uniq = np.unique(orig_ids)
                    tgt[:] = 0.0
                    tgt._set_data(tgt._data.at[
                        jnp.asarray(uniq, "int32")].set(gather(uniq)))
                else:
                    raise MXNetError(
                        "row_sparse_pull: target shape %s matches neither "
                        "the request (%d rows) nor the store %s"
                        % (tgt.shape, len(orig_ids), src.shape))

    # -- optimizer plumbing --------------------------------------------
    def set_optimizer(self, optimizer):
        """Install the optimizer server-side (reference pickles it to the
        ps-lite servers via ``_send_command_to_servers``; here every
        replica runs it identically inside the same program)."""
        from . import optimizer as opt

        # round-trip through pickle to mirror the reference contract that
        # the optimizer must be serializable
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    @property
    def updater(self):
        return self._updater

    # -- async (bounded-staleness) parameter averaging ------------------
    def sync_params(self, arrays):
        """Average parameter arrays across processes (one blocking DCN
        collective per array) — the dist_async averaging round.  Every
        process must call this the same number of times.  No-op
        single-process."""
        import jax

        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        def _average():
            for arr in arrays:
                gathered = multihost_utils.process_allgather(arr._data)
                arr._set_data(jax.device_put(gathered.mean(axis=0)))

        self._bounded_collective(
            _average, "KVStore.sync_params (parameter-averaging round)",
            retries=0)

    def _async_tick(self, arrays):
        """Count one local update; run an averaging round every
        ``MXNET_ASYNC_SYNC_PERIOD`` updates (0 = epoch-end rounds only,
        driven by the trainer).  ``arrays`` may be a callable returning
        the list, so callers skip building it when no round fires."""
        if not self._is_async:
            return
        self._async_steps += 1
        if self._async_period > 0 and \
                self._async_steps % self._async_period == 0:
            self.sync_params(arrays() if callable(arrays) else arrays)

    # -- barriers / control --------------------------------------------
    def barrier(self):
        """Global barrier (reference ``MXKVStoreBarrier``).  Under SPMD all
        replicas run in lockstep inside compiled steps; between steps we
        drain local async work, and multi-process stores additionally
        rendezvous over DCN — bounded by ``MXNET_KV_TIMEOUT_S`` so one
        dead rank surfaces as an MXNetError on the survivors instead of
        an eternal hang (checkpoint rank-0-writes relies on this)."""
        from .ndarray import waitall

        waitall()
        if not self._is_dist:
            return
        import jax

        if jax.process_count() <= 1:
            return

        def _rendezvous():
            from .testing import faults

            faults.inject("collective")
            import numpy as np
            from jax.experimental import multihost_utils

            multihost_utils.process_allgather(np.zeros((1,), "int32"))

        _run_bounded(_rendezvous, "KVStore.barrier (DCN rendezvous)",
                     diagnose=self._peer_diagnose)

    def _peer_diagnose(self):
        """Heartbeat-based liveness summary appended to collective
        timeout errors ('' when heartbeats are unconfigured)."""
        from .health import peer_report

        return peer_report(self.num_workers, self_rank=self.rank)

    def close(self):
        """Stop background liveness machinery (the heartbeat thread).
        Safe to call multiple times; the store stays usable for local
        ops afterwards."""
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            hb.stop()
            self._heartbeat = None

    def _bounded_collective(self, fn, what, retries=None):
        """Run a cross-process collective under the KV timeout (identity
        wrapper single-process — no helper thread on the hot local
        path).  Site ``collective`` of the fault harness fires first, so
        tests can wedge/fail the DCN path deterministically.  Pass
        ``retries=0`` for calls that mutate state in place (a partial
        retry would re-reduce already-reduced values)."""
        import jax

        if jax.process_count() <= 1:
            return fn()

        def _go():
            from .testing import faults

            faults.inject("collective")
            return fn()

        if retries is None:
            retries = get_env("MXNET_KV_RETRIES", 2, int)
        return _run_bounded(_go, what, retries=retries,
                            diagnose=self._peer_diagnose)

    def _send_command_to_servers(self, head, body):
        pass  # no servers in the TPU design

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Write the updater's optimizer states atomically (temp +
        ``os.replace``).  Rank-0-writes contract: non-rank-0 callers are
        a graceful no-op, so symmetric SPMD scripts can call this
        unconditionally without N ranks racing on one file."""
        if self._updater is None:
            raise MXNetError(
                "save_optimizer_states needs a worker-side updater: call "
                "set_optimizer (update_on_kvstore) first — with updates "
                "running outside the store there are no states here to "
                "save")
        if self.rank != 0:
            logger.debug("save_optimizer_states: rank %d skips the write "
                         "(rank 0 owns the file)", self.rank)
            return
        payload = self._updater.get_states()
        from .checkpoint import atomic_replace

        def _write(tmp):
            with open(tmp, "wb") as f:
                f.write(payload)

        atomic_replace(fname, _write)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError(
                "load_optimizer_states needs a worker-side updater: call "
                "set_optimizer (update_on_kvstore) first")
        if not os.path.exists(fname):
            raise MXNetError(
                "optimizer states file %r does not exist — was the "
                "checkpoint written with save_optimizer_states on rank 0, "
                "and is its directory visible from this rank?" % fname)
        try:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError("optimizer states file %r is corrupt: %s"
                             % (fname, e)) from e

    # -- internals ------------------------------------------------------
    @staticmethod
    def _normalize(key, value, allow_list=False):
        if isinstance(key, (str, int)):
            return [key], [value]
        assert len(key) == len(value)
        return list(key), list(value)

    @staticmethod
    def _key_index(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @staticmethod
    def _reduce(vs):
        from .ndarray.sparse import (BaseSparseNDArray, RowSparseNDArray)
        from .ndarray import sparse as _sp

        if isinstance(vs, NDArray) and not isinstance(vs,
                                                      BaseSparseNDArray):
            return vs
        if isinstance(vs, BaseSparseNDArray):
            return vs
        if len(vs) == 1:
            return vs[0]
        if all(isinstance(v, RowSparseNDArray) for v in vs):
            return _sp.add_n(list(vs))  # sparse merge, no densify
        vs = [v.todense() if isinstance(v, BaseSparseNDArray) else v
              for v in vs]
        return imperative_invoke("add_n", list(vs), {})[0]

    def _cross_replica_sum_flat(self, arrays):
        """One DCN round trip for a list of dense NDArrays: flatten,
        concatenate (per dtype), allreduce once, split back.  Replaces
        the per-key host bounce of the split push path (VERDICT r3
        weak 7 — O(P·keys) round trips become O(P·dtypes))."""
        import jax.numpy as jnp

        from .parallel import collectives

        by_dtype = {}
        for i, a in enumerate(arrays):
            by_dtype.setdefault(str(a._data.dtype), []).append(i)
        out = list(arrays)
        for idxs in by_dtype.values():
            flat = jnp.concatenate(
                [arrays[i]._data.ravel() for i in idxs])
            red = collectives.allreduce_nd(
                NDArray(flat, arrays[idxs[0]].context))._data
            off = 0
            for i in idxs:
                n = arrays[i]._data.size
                out[i] = NDArray(
                    red[off:off + n].reshape(arrays[i]._data.shape),
                    arrays[i].context)
                off += n
        return out

    def _cross_replica_sum(self, arr, is_partial_stack=False):
        """All-reduce across replicas: over the active mesh's data axis
        for per-chip partial gradients (ICI collective, requires the
        caller to declare the stack via ``is_partial_stack``), over DCN
        for multi-process values; identity when the pushed gradient is
        already global (the fused SPMD step's case).  The multi-process
        branch runs under the ``MXNET_KV_TIMEOUT_S`` bound: a wedged
        peer raises instead of deadlocking the push."""
        from .parallel import collectives
        from .parallel.mesh import current_mesh

        mesh = getattr(self, "_mesh", None) or current_mesh()
        if is_partial_stack:  # pure in-chip reduce, no DCN to wedge on
            return collectives.allreduce_nd(arr, mesh=mesh,
                                            is_partial_stack=True)
        return self._bounded_collective(
            lambda: collectives.allreduce_nd(
                arr, mesh=mesh, is_partial_stack=is_partial_stack),
            "KVStore cross-replica gradient sum")
