"""Server-role entry point (reference ``python/mxnet/kvstore_server.py``).

In the reference, processes launched with ``DMLC_ROLE=server`` never return
from ``import mxnet``: ``_init_kvstore_server_module`` (`kvstore_server.py:75-85`)
detects the role and blocks in ``KVStoreServer.run`` — a C++ request loop
(``src/kvstore/kvstore_dist_server.h:139``) that merges worker pushes and
applies the pickled optimizer.

The TPU design has **no server processes**: ``dist_tpu_sync`` is SPMD — the
optimizer runs inside every worker's compiled step and the gradient merge is
an XLA all-reduce over ICI (see ``parallel/collectives.py``).  This module
keeps launcher compatibility: a process started with the server role simply
joins the coordination service (so ``jax.distributed`` rendezvous still
counts it) and exits cleanly, and ``KVStoreServer`` exists so scripts that
instantiate it don't crash.  ``tools/launch.py`` therefore never needs ``-s``
servers; it warns if asked for them.
"""
from __future__ import annotations

import logging
import os
import pickle

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """API-compatible stand-in for the reference server wrapper
    (``kvstore_server.py:28``)."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handle = getattr(kvstore, "handle", None)
        self.init_logging = False

    def _controller(self):
        """Reference servers receive pickled optimizers via
        ``_send_command_to_servers``; under SPMD the optimizer already
        lives in the worker step, so commands are logged and dropped."""

        def server_controller(cmd_id, cmd_body):
            if cmd_id == 3:  # kController_SetOptimizer in the reference
                try:
                    pickle.loads(cmd_body.encode("latin1"))
                except Exception:
                    pass
            logging.getLogger(__name__).info(
                "kvstore server command (%d) ignored: SPMD workers own "
                "the optimizer", cmd_id)

        return server_controller

    def run(self):
        """Return immediately: there is no server request loop to block in.
        The reference blocks here forever (``KVStoreDistServer::Run``)."""
        logging.getLogger(__name__).warning(
            "KVStoreServer.run(): dist_tpu_sync has no parameter servers; "
            "returning (role treated as a no-op participant)")


def _init_kvstore_server_module():
    """Role dispatch at import (reference ``kvstore_server.py:75-85``)."""
    role = os.environ.get("DMLC_ROLE", os.environ.get("MXNET_ROLE", ""))
    if role == "server":
        from . import kvstore

        server = KVStoreServer(kvstore.create("dist_tpu_sync"))
        server.run()
        raise SystemExit(0)
    # workers and schedulers fall through to a normal import


if os.environ.get("DMLC_ROLE", os.environ.get("MXNET_ROLE", "")) \
        == "server" and \
        os.environ.get("MXNET_KVSTORE_SERVER_AUTORUN", "1") == "1":
    _init_kvstore_server_module()
