"""Library metadata and native-library discovery (reference
``python/mxnet/libinfo.py``: ``find_lib_path`` locates ``libmxnet.so``;
``__version__`` is read from it).

Here the native component is the RecordIO scanner built from
``src/recordio.cc`` at first use (see ``mxnet_tpu/_native.py``); everything
else executes through XLA/PJRT, which jax itself loads.  ``find_lib_path``
returns the built shared objects so deployment scripts that bundle
"the native libs" keep working.
"""
from __future__ import annotations

import os

__all__ = ["find_lib_path", "find_include_path", "__version__"]

__version__ = "0.1.0"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def find_lib_path():
    """Paths of the framework's compiled native libraries (may build them
    on first call; empty list when no compiler is available)."""
    from . import _native

    libs = []
    if _native.native_recordio() is not None:
        libs.append(os.path.join(_native._BUILD_DIR, "recordio.so"))
    return libs


def find_include_path():
    """Native sources shipped in place of a C header tree."""
    return os.path.join(_REPO, "src")
