"""Learning-rate schedulers (reference ``python/mxnet/lr_scheduler.py``)."""
from __future__ import annotations

import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every ``step`` updates (reference ``FactorScheduler``)."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01):
        super().__init__(base_lr)
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at each listed step (reference ``MultiFactorScheduler``)."""

    def __init__(self, step, factor=1.0, base_lr=0.01):
        super().__init__(base_lr)
        assert isinstance(step, list) and len(step) >= 1
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
            else:
                return self.base_lr
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay to zero over max_update steps."""

    def __init__(self, max_update, base_lr=0.01, pwr=2):
        super().__init__(base_lr)
        self.max_update = max_update
        self.power = pwr
        self.base_lr_orig = base_lr

    def __call__(self, num_update):
        if num_update <= self.max_update:
            self.base_lr = self.base_lr_orig * pow(
                1.0 - float(num_update) / float(self.max_update), self.power)
        return self.base_lr


class CosineScheduler(LRScheduler):
    """Cosine decay with optional warmup (TPU-era addition; not in the
    reference but standard for the model zoo recipes)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0.0, warmup_steps=0):
        super().__init__(base_lr)
        self.max_update = max_update
        self.final_lr = final_lr
        self.warmup_steps = warmup_steps
        self.base_lr_orig = base_lr

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.base_lr_orig * num_update / max(1, self.warmup_steps)
        t = min(num_update - self.warmup_steps,
                self.max_update - self.warmup_steps)
        T = max(1, self.max_update - self.warmup_steps)
        return self.final_lr + (self.base_lr_orig - self.final_lr) * \
            0.5 * (1 + math.cos(math.pi * t / T))
