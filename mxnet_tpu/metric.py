"""Evaluation metrics (reference ``python/mxnet/metric.py``, 1,132 LoC).

Full reference family: Accuracy, TopKAccuracy, F1, Perplexity, MAE, MSE,
RMSE, CrossEntropy, Loss, Torch, Caffe, CustomMetric, CompositeEvalMetric,
np() wrapper, create() registry.
"""
from __future__ import annotations

import logging
import math

import numpy as _np

from .base import MXNetError, _Registry
from .ndarray import NDArray

_logger = logging.getLogger(__name__)

__all__ = ["EvalMetric", "CompositeEvalMetric", "LazyEvalMetric",
           "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "CustomMetric", "np", "create", "register"]

_registry = _Registry("metric")


def register(klass, *names):
    for n in (names or [klass.__name__.lower()]):
        _registry.register(n, klass)
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _registry.get(str(metric).lower())(*args, **kwargs)


def _as_numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(label_shape, pred_shape))


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.num_nonfinite = 0  # subclasses may override reset()
        self.reset()

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def _accumulate(self, sum_inc, num_inc):
        """Fold one increment into the running sums — unless it is
        non-finite.  A single NaN batch would otherwise poison
        ``sum_metric`` for the rest of the epoch (nan + x == nan), so a
        bad increment is *dropped* and counted in ``num_nonfinite``
        instead, with a throttled warning so the drop is visible."""
        if not _np.all(_np.isfinite(sum_inc)):
            self.num_nonfinite += 1
            if self.num_nonfinite == 1 or self.num_nonfinite % 100 == 0:
                _logger.warning(
                    "metric %s: dropped non-finite update #%d (value %r); "
                    "the running metric excludes these batches",
                    self.name, self.num_nonfinite, sum_inc)
            return
        self.sum_metric += sum_inc
        self.num_inst += num_inc

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.num_nonfinite = 0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name)
            values.append(value)
        return names, values


class LazyEvalMetric(EvalMetric):
    """Deferred-sync wrapper for the pipelined training loop.

    Every built-in metric's ``update`` calls ``asnumpy`` on its inputs —
    a host sync that blocks the dispatch thread until the step that
    produced them finishes, serializing the loop with the device.  This
    wrapper instead *buffers references* to the (labels, preds) device
    arrays (cheap: JAX arrays are immutable, so late evaluation sees the
    right values) and replays them into the wrapped metric only at a sync
    point: an explicit :meth:`flush`, any ``get``/``get_name_value``
    (which is what ``batch_end_callback`` loggers like ``Speedometer``
    call — so the sync cadence auto-aligns with the callback interval),
    or every ``sync_period`` updates as a buffer bound.

    ``Module.fit(metric_sync_period=K)`` wraps the training metric in
    this automatically for K > 1.
    """

    def __init__(self, base, sync_period=None, **kwargs):
        self._base = create(base)
        self._pending = []
        self._sync_period = sync_period
        super().__init__(self._base.name, **kwargs)

    def update(self, labels, preds):
        self._pending.append((list(labels or []), list(preds)))
        if self._sync_period and len(self._pending) >= self._sync_period:
            self.flush()

    def flush(self):
        """Replay buffered updates into the wrapped metric (the host
        sync happens here)."""
        pending, self._pending = self._pending, []
        for labels, preds in pending:
            self._base.update(labels, preds)

    def reset(self):
        self._pending = []
        self._base.reset()

    def get(self):
        self.flush()
        return self._base.get()


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            label = label.astype("int32").ravel()
            pred = pred.astype("int32").ravel()
            check_label_shapes(label, pred, shape=1)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.top_k = top_k
        assert self.top_k > 1, "top_k should be >1; use Accuracy for top_k=1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            pred_idx = _np.argsort(pred.astype("float32"), axis=1)
            num_samples, num_classes = pred_idx.shape
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += (
                    pred_idx[:, num_classes - 1 - j].ravel() ==
                    label.astype("int32").ravel()).sum()
            self.num_inst += num_samples


@register
class F1(EvalMetric):
    """Binary F1 (reference F1: predictions argmax'd, label in {0,1})."""

    def __init__(self, name="f1", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=1)
            pred = pred.ravel()
            if not set(_np.unique(label)).issubset({0., 1.}):
                raise ValueError("F1 currently only supports binary labels")
            tp = ((pred == 1) & (label == 1)).sum()
            fp = ((pred == 1) & (label == 0)).sum()
            fn = ((pred == 0) & (label == 1)).sum()
            precision = tp / (tp + fp) if tp + fp > 0 else 0.
            recall = tp / (tp + fn) if tp + fn > 0 else 0.
            if precision + recall > 0:
                self.sum_metric += 2 * precision * recall / (precision + recall)
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        loss, num = 0., 0
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.astype("int32").ravel()
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss += -_np.log(_np.maximum(1e-10, probs)).sum()
            num += label.shape[0]
        try:
            ppl = math.exp(loss / max(1, num))
        except OverflowError:  # exp(huge finite loss) — treat as inf
            ppl = float("inf")
        self._accumulate(ppl, 1)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._accumulate(_np.abs(label - pred).mean(), 1)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._accumulate(((label - pred) ** 2.0).mean(), 1)


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._accumulate(_np.sqrt(((label - pred) ** 2.0).mean()), 1)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self._accumulate((-_np.log(prob + self.eps)).sum(),
                             label.shape[0])


@register
class Loss(EvalMetric):
    """Mean of raw outputs (for MakeLoss-style heads)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in preds:
            pred = _as_numpy(pred)
            self._accumulate(pred.sum(), pred.size)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False, **kwargs):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label, pred = _as_numpy(label), _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self._accumulate(sum_metric, num_inst)
            else:
                self._accumulate(reval, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference ``metric.np``)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = name if name else numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


register(CrossEntropy, "ce", "crossentropy", "cross-entropy")
register(Accuracy, "acc")
register(TopKAccuracy, "top_k_accuracy", "top_k_acc")
