"""Checkpoint helpers (reference ``python/mxnet/model.py:340-404``).

Format contract preserved: ``prefix-symbol.json`` holds the graph JSON,
``prefix-%04d.params`` holds a flat dict of arrays with ``arg:``/``aux:``
name prefixes.  The container for params is ``.npz`` instead of the
dmlc::Stream binary (documented divergence; keys and layout match, so
``load_checkpoint``/``save_checkpoint`` round-trip the same dicts).
"""
from __future__ import annotations

import os

from .base import MXNetError
from . import symbol as sym_mod
from .ndarray import NDArray, save as nd_save, load as nd_load

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam",
           "FeedForward"]

from .module.base_module import BatchEndParam  # re-export (reference home)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Atomic: both files are written to temp names and published with
    ``os.replace`` (:func:`mxnet_tpu.checkpoint.atomic_replace`), so a
    crash mid-save can never leave a partial ``-symbol.json``/``.params``
    pair on disk — a previous checkpoint under the same prefix survives
    untouched."""
    from .checkpoint import atomic_replace

    if symbol is not None:
        atomic_replace("%s-symbol.json" % prefix,
                       lambda tmp: symbol.save(tmp))
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)

    def _write(tmp):
        nd_save(tmp, save_dict)
        # numpy appends .npz to extension-less names; report the real
        # temp file so the rename publishes the reference filename
        return tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp

    atomic_replace(param_name, _write)


def load_checkpoint(prefix, epoch):
    symbol_file = "%s-symbol.json" % prefix
    param_name = "%s-%04d.params" % (prefix, epoch)
    if not os.path.exists(symbol_file):
        raise MXNetError(
            "checkpoint %r has no symbol file: %s is missing"
            % (prefix, symbol_file))
    try:
        symbol = sym_mod.load(symbol_file)
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError("checkpoint symbol file %s is corrupt: %s"
                         % (symbol_file, e)) from e
    if not os.path.exists(param_name):
        raise MXNetError(
            "checkpoint %r has no params for epoch %d: %s is missing"
            % (prefix, epoch, param_name))
    try:
        save_dict = nd_load(param_name)
    except Exception as e:
        raise MXNetError("checkpoint params file %s is corrupt: %s"
                         % (param_name, e)) from e
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("invalid param key %r" % k)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy estimator API (reference ``model.py:408`` ``FeedForward`` —
    deprecated there in favor of Module; provided for script parity and
    implemented as a thin veneer over :class:`~mxnet_tpu.module.Module`,
    exactly the migration the reference documentation prescribes)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        from .initializer import Uniform

        self._symbol = symbol
        self._ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._opt_kwargs = kwargs
        self._module = None

    @property
    def symbol(self):
        return self._symbol

    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        # reference FeedForward clamps to the dataset size
        batch = min(self.numpy_batch_size, len(X))
        return NDArrayIter(X, y, batch_size=batch, shuffle=shuffle)

    def _build_module(self, data_iter):
        from .module import Module

        # label variables by symbol convention (reference FeedForward
        # keys on the *_label suffix), so predict without labels still
        # classifies them as labels rather than parameters
        label_names = [n for n in self._symbol.list_arguments()
                       if n.endswith("_label")]
        self._module = Module(self._symbol, context=self._ctx,
                              label_names=tuple(label_names))
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        """Train (reference ``FeedForward.fit`` → ``_train_multi_device``,
        ``model.py:152``)."""
        train = self._as_iter(X, y, shuffle=True)
        mod = self._build_module(train)
        mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self._opt_kwargs,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor, eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Predict outputs as a numpy array; with ``return_data`` also
        return the consumed (data, labels) like the reference
        ``FeedForward.predict``."""
        import numpy as np

        data = self._as_iter(X)
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label or None,
                     for_training=False)
            mod.init_params(arg_params=self.arg_params,
                            aux_params=self.aux_params)
        mod = self._module
        if reset:
            data.reset()
        outs, datas, labels = [], [], []
        for i, batch in enumerate(data):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            keep = mod.get_outputs()[0].shape[0] - (batch.pad or 0)
            outs.append(mod.get_outputs()[0].asnumpy()[:keep])
            if return_data:
                datas.append(batch.data[0].asnumpy()[:keep])
                if batch.label:
                    labels.append(batch.label[0].asnumpy()[:keep])
        result = np.concatenate(outs, axis=0)
        if return_data:
            return (result, np.concatenate(datas, axis=0),
                    np.concatenate(labels, axis=0) if labels else None)
        return result

    def score(self, X, eval_metric="acc", num_batch=None):
        """Evaluate (reference ``FeedForward.score``)."""
        from .metric import create as metric_create

        data = self._as_iter(X)
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label or None,
                     for_training=False)
            mod.init_params(arg_params=self.arg_params,
                            aux_params=self.aux_params)
        metric = metric_create(eval_metric) \
            if isinstance(eval_metric, str) else eval_metric
        res = self._module.score(data, metric, num_batch=num_batch)
        return dict(res).popitem()[1]

    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        if epoch is None:
            raise MXNetError("FeedForward.save needs an epoch (num_epoch "
                             "was not set on this model)")
        save_checkpoint(prefix, epoch, self._symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        sym, arg, aux = load_checkpoint(prefix, epoch)
        return FeedForward(sym, ctx=ctx, arg_params=arg, aux_params=aux,
                           begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, **kwargs):
        """Build and fit in one call (reference ``FeedForward.create``)."""
        fit_keys = ("eval_data", "eval_metric", "epoch_end_callback",
                    "batch_end_callback", "kvstore", "logger", "monitor",
                    "eval_end_callback", "eval_batch_end_callback",
                    "work_load_list")
        fit_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                      if k in fit_keys}
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            **kwargs)
        return model.fit(X, y, **fit_kwargs)
