"""Checkpoint helpers (reference ``python/mxnet/model.py:340-404``).

Format contract preserved: ``prefix-symbol.json`` holds the graph JSON,
``prefix-%04d.params`` holds a flat dict of arrays with ``arg:``/``aux:``
name prefixes.  The container for params is ``.npz`` instead of the
dmlc::Stream binary (documented divergence; keys and layout match, so
``load_checkpoint``/``save_checkpoint`` round-trip the same dicts).
"""
from __future__ import annotations

import os

from .base import MXNetError
from . import symbol as sym_mod
from .ndarray import NDArray, save as nd_save, load as nd_load

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

from .module.base_module import BatchEndParam  # re-export (reference home)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)
    # numpy appends .npz; keep the reference filename
    if os.path.exists(param_name + ".npz"):
        os.replace(param_name + ".npz", param_name)


def load_checkpoint(prefix, epoch):
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    param_name = "%s-%04d.params" % (prefix, epoch)
    save_dict = nd_load(param_name)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("invalid param key %r" % k)
    return symbol, arg_params, aux_params
