"""Model zoo: Symbol generators for the reference's example networks.

Covers ``example/image-classification/symbols/`` (lenet, mlp, alexnet,
vgg, resnet, inception-bn, mobilenet) plus the post-reference
transformer LM family (``transformer.py`` — see ``bench_transformer.py``
for its MFU numbers).  Each returns a Symbol ending in SoftmaxOutput,
ready for ``Module``.
"""
from . import lenet
from . import mlp
from . import alexnet
from . import vgg
from . import resnet
from . import inception_bn
from . import mobilenet
from . import inception_v3
from . import transformer

__all__ = ["lenet", "mlp", "alexnet", "vgg", "resnet", "inception_bn",
           "mobilenet", "inception_v3", "transformer", "get_model"]

_MODELS = {m.__name__.rsplit(".", 1)[-1]: m.get_symbol
           for m in (lenet, mlp, alexnet, vgg, resnet, inception_bn,
                     mobilenet, inception_v3, transformer)}


def get_model(name, **kwargs):
    from ..base import MXNetError

    if name not in _MODELS:
        raise MXNetError("unknown model %r (have: %s)"
                         % (name, sorted(_MODELS)))
    return _MODELS[name](**kwargs)
