"""Inception V3 (reference
``example/image-classification/symbols/inception-v3.py``; the
Szegedy et al. 2015 architecture, input 299x299).  One of the reference's
distributed-training flagship configs (BASELINE scaling tables)."""
from .. import symbol as sym


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=None):
    c = sym.Convolution(data, kernel=kernel, stride=stride, pad=pad,
                        num_filter=num_filter, no_bias=True,
                        name="%s_conv" % name)
    bn = sym.BatchNorm(c, fix_gamma=True, eps=0.001,
                       name="%s_bn" % name)
    return sym.Activation(bn, act_type="relu", name="%s_relu" % name)


def _pool(data, kernel, stride, pad, pool_type, name):
    return sym.Pooling(data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


def _inception_a(data, b1, b2_1, b2_2, b3_1, b3_2, b4, name):
    t1 = _conv(data, b1, name="%s_1x1" % name)
    t2 = _conv(data, b2_1, name="%s_5x5r" % name)
    t2 = _conv(t2, b2_2, kernel=(5, 5), pad=(2, 2), name="%s_5x5" % name)
    t3 = _conv(data, b3_1, name="%s_3x3r" % name)
    t3 = _conv(t3, b3_2, kernel=(3, 3), pad=(1, 1),
               name="%s_3x3a" % name)
    t3 = _conv(t3, b3_2, kernel=(3, 3), pad=(1, 1),
               name="%s_3x3b" % name)
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    t4 = _conv(t4, b4, name="%s_proj" % name)
    return sym.Concat(t1, t2, t3, t4, name="%s_concat" % name)


def _reduction_a(data, b3, b23_1, b23_2, b23_3, name):
    t1 = _conv(data, b3, kernel=(3, 3), stride=(2, 2),
               name="%s_3x3" % name)
    t2 = _conv(data, b23_1, name="%s_d3x3r" % name)
    t2 = _conv(t2, b23_2, kernel=(3, 3), pad=(1, 1),
               name="%s_d3x3a" % name)
    t2 = _conv(t2, b23_3, kernel=(3, 3), stride=(2, 2),
               name="%s_d3x3b" % name)
    t3 = _pool(data, (3, 3), (2, 2), (0, 0), "max", "%s_pool" % name)
    return sym.Concat(t1, t2, t3, name="%s_concat" % name)


def _inception_b(data, b7, name):
    t1 = _conv(data, 192, name="%s_1x1" % name)
    t2 = _conv(data, b7, name="%s_7x7r" % name)
    t2 = _conv(t2, b7, kernel=(1, 7), pad=(0, 3), name="%s_1x7a" % name)
    t2 = _conv(t2, 192, kernel=(7, 1), pad=(3, 0), name="%s_7x1a" % name)
    t3 = _conv(data, b7, name="%s_d7r" % name)
    t3 = _conv(t3, b7, kernel=(7, 1), pad=(3, 0), name="%s_7x1b" % name)
    t3 = _conv(t3, b7, kernel=(1, 7), pad=(0, 3), name="%s_1x7b" % name)
    t3 = _conv(t3, b7, kernel=(7, 1), pad=(3, 0), name="%s_7x1c" % name)
    t3 = _conv(t3, 192, kernel=(1, 7), pad=(0, 3), name="%s_1x7c" % name)
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    t4 = _conv(t4, 192, name="%s_proj" % name)
    return sym.Concat(t1, t2, t3, t4, name="%s_concat" % name)


def _reduction_b(data, name):
    t1 = _conv(data, 192, name="%s_3x3r" % name)
    t1 = _conv(t1, 320, kernel=(3, 3), stride=(2, 2),
               name="%s_3x3" % name)
    t2 = _conv(data, 192, name="%s_7x7r" % name)
    t2 = _conv(t2, 192, kernel=(1, 7), pad=(0, 3), name="%s_1x7" % name)
    t2 = _conv(t2, 192, kernel=(7, 1), pad=(3, 0), name="%s_7x1" % name)
    t2 = _conv(t2, 192, kernel=(3, 3), stride=(2, 2),
               name="%s_3x3b" % name)
    t3 = _pool(data, (3, 3), (2, 2), (0, 0), "max", "%s_pool" % name)
    return sym.Concat(t1, t2, t3, name="%s_concat" % name)


def _inception_c(data, name):
    t1 = _conv(data, 320, name="%s_1x1" % name)
    t2 = _conv(data, 384, name="%s_3x3r" % name)
    t2a = _conv(t2, 384, kernel=(1, 3), pad=(0, 1), name="%s_1x3" % name)
    t2b = _conv(t2, 384, kernel=(3, 1), pad=(1, 0), name="%s_3x1" % name)
    t3 = _conv(data, 448, name="%s_d3r" % name)
    t3 = _conv(t3, 384, kernel=(3, 3), pad=(1, 1), name="%s_d3" % name)
    t3a = _conv(t3, 384, kernel=(1, 3), pad=(0, 1),
                name="%s_d1x3" % name)
    t3b = _conv(t3, 384, kernel=(3, 1), pad=(1, 0),
                name="%s_d3x1" % name)
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    t4 = _conv(t4, 192, name="%s_proj" % name)
    return sym.Concat(t1, t2a, t2b, t3a, t3b, t4,
                      name="%s_concat" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")  # (N, 3, 299, 299)
    net = _conv(data, 32, kernel=(3, 3), stride=(2, 2), name="stem1")
    net = _conv(net, 32, kernel=(3, 3), name="stem2")
    net = _conv(net, 64, kernel=(3, 3), pad=(1, 1), name="stem3")
    net = _pool(net, (3, 3), (2, 2), (0, 0), "max", "stem_pool1")
    net = _conv(net, 80, name="stem4")
    net = _conv(net, 192, kernel=(3, 3), name="stem5")
    net = _pool(net, (3, 3), (2, 2), (0, 0), "max", "stem_pool2")

    net = _inception_a(net, 64, 48, 64, 64, 96, 32, "mixed0")
    net = _inception_a(net, 64, 48, 64, 64, 96, 64, "mixed1")
    net = _inception_a(net, 64, 48, 64, 64, 96, 64, "mixed2")
    net = _reduction_a(net, 384, 64, 96, 96, "mixed3")
    net = _inception_b(net, 128, "mixed4")
    net = _inception_b(net, 160, "mixed5")
    net = _inception_b(net, 160, "mixed6")
    net = _inception_b(net, 192, "mixed7")
    net = _reduction_b(net, "mixed8")
    net = _inception_c(net, "mixed9")
    net = _inception_c(net, "mixed10")

    pool = sym.Pooling(net, kernel=(8, 8), global_pool=True,
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
