"""MLP (reference ``example/image-classification/symbols/mlp.py``)."""
from .. import symbol as sym


def get_symbol(num_classes=10, **kwargs):
    data = sym.Variable("data")
    data = sym.Flatten(data)
    fc1 = sym.FullyConnected(data, num_hidden=128, name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, num_hidden=64, name="fc2")
    act2 = sym.Activation(fc2, act_type="relu", name="relu2")
    fc3 = sym.FullyConnected(act2, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(fc3, name="softmax")
