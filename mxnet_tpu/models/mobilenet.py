"""MobileNet v1 (reference
``example/image-classification/symbols/mobilenet.py``): depthwise-
separable convolutions — depthwise 3x3 (grouped Convolution with
num_group == channels) followed by pointwise 1x1 — each with BN + ReLU.
On TPU the depthwise conv lowers to an XLA feature-group convolution.
"""
from .. import symbol as sym


def _conv_block(data, num_filter, kernel, stride, pad, name,
                num_group=1):
    conv = sym.Convolution(data, kernel=kernel, stride=stride, pad=pad,
                           num_filter=num_filter, num_group=num_group,
                           no_bias=True, name=name)
    bn = sym.BatchNorm(conv, fix_gamma=False, name="%s_bn" % name)
    return sym.Activation(bn, act_type="relu", name="%s_relu" % name)


def _dw_sep(data, in_ch, out_ch, stride, idx, multiplier):
    in_ch = int(in_ch * multiplier)
    out_ch = int(out_ch * multiplier)
    dw = _conv_block(data, in_ch, (3, 3), stride, (1, 1),
                     "conv%d_dw" % idx, num_group=in_ch)
    return _conv_block(dw, out_ch, (1, 1), (1, 1), (0, 0),
                       "conv%d_pw" % idx)


def get_symbol(num_classes=1000, multiplier=1.0, **kwargs):
    data = sym.Variable("data")
    net = _conv_block(data, int(32 * multiplier), (3, 3), (2, 2), (1, 1),
                      "conv1")
    spec = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
           [(512, 1024, 2), (1024, 1024, 1)]
    for i, (cin, cout, s) in enumerate(spec, start=2):
        net = _dw_sep(net, cin, cout, (s, s), i, multiplier)
    pool = sym.Pooling(net, kernel=(7, 7), global_pool=True,
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")
