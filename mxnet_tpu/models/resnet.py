"""ResNet (reference ``example/image-classification/symbols/resnet.py``,
the Kaiming-He v2 pre-activation form used for the published baselines in
BASELINE.md).  Depths: 18/34 (basic block), 50/101/152/200 (bottleneck).

This is the flagship benchmark network: ResNet-50 fwd+bwd img/s is the
headline number (reference: 109 img/s on K80, BASELINE.md).

``layout`` may be 'NCHW' (the reference default) or 'NHWC' — the
TPU-native layout: channels ride the 128-lane dimension, so BatchNorm
reductions are lane-parallel and convolutions avoid relayouts (measured
~25% faster fused train step on v5e)."""
from .. import symbol as sym


def _bn_axis(layout):
    return 3 if layout == "NHWC" else 1


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True, bn_mom=0.9, layout="NCHW"):
    ax = _bn_axis(layout)
    if bottle_neck:
        bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                            axis=ax, name=name + "_bn1")
        act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(act1, num_filter=num_filter // 4,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, layout=layout,
                                name=name + "_conv1")
        bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, axis=ax, name=name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(act2, num_filter=num_filter // 4,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, layout=layout,
                                name=name + "_conv2")
        bn3 = sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, axis=ax, name=name + "_bn3")
        act3 = sym.Activation(bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(act3, num_filter=num_filter, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True,
                                layout=layout, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(act1, num_filter=num_filter,
                                       kernel=(1, 1), stride=stride,
                                       no_bias=True, layout=layout,
                                       name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                        axis=ax, name=name + "_bn1")
    act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(act1, num_filter=num_filter, kernel=(3, 3),
                            stride=stride, pad=(1, 1), no_bias=True,
                            layout=layout, name=name + "_conv1")
    bn2 = sym.BatchNorm(conv1, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                        axis=ax, name=name + "_bn2")
    act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            layout=layout, name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(act1, num_filter=num_filter,
                                   kernel=(1, 1), stride=stride,
                                   no_bias=True, layout=layout,
                                   name=name + "_sc")
    return conv2 + shortcut


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9, layout="NCHW"):
    ax = _bn_axis(layout)
    data = sym.Variable("data")
    nchannel, height, _ = image_shape
    data = sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=bn_mom,
                         axis=ax, name="bn_data")
    if height <= 32:  # CIFAR
        body = sym.Convolution(data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, layout=layout, name="conv0")
    else:  # ImageNet
        body = sym.Convolution(data, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, layout=layout, name="conv0")
        body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                             momentum=bn_mom, axis=ax, name="bn0")
        body = sym.Activation(body, act_type="relu", name="relu0")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", layout=layout)

    for i in range(num_stages):
        body = residual_unit(body, filter_list[i + 1],
                             (1 if i == 0 else 2,) * 2, False,
                             name="stage%d_unit%d" % (i + 1, 1),
                             bottle_neck=bottle_neck, bn_mom=bn_mom,
                             layout=layout)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck=bottle_neck, bn_mom=bn_mom,
                                 layout=layout)
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        axis=ax, name="bn1")
    relu1 = sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(relu1, global_pool=True, kernel=(7, 7),
                        pool_type="avg", layout=layout, name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc1, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               layout="NCHW", **kwargs):
    """Build a ResNet symbol (reference ``resnet.py`` ``get_symbol``).

    ``image_shape`` is always given channel-first (C, H, W) like the
    reference; with ``layout='NHWC'`` the bound data shape must be
    (N, H, W, C)."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    height = image_shape[1]
    if height <= 32:  # CIFAR-style
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            bottle_neck = False
        else:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = per_unit * num_stages
    else:
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            bottle_neck = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            bottle_neck = False
        num_stages = 4
        unit_map = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                    101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
                    200: [3, 24, 36, 3]}
        if num_layers not in unit_map:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = unit_map[num_layers]
    return resnet(units, num_stages, filter_list, num_classes, image_shape,
                  bottle_neck, layout=layout)
