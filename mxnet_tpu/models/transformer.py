"""Decoder-only transformer language model (GPT-style).

Not in the 0.11 reference — the modern flagship workload this framework
adds on top of the reference's capability surface.  Built from the same
symbolic ops as every other model (``FullyConnected``, ``LayerNorm``,
``MultiHeadAttention``, ``Embedding``) so it trains through the identical
``Module``/``TrainStep`` machinery, and shaped TPU-first: all FLOPs in
large matmuls (MXU), pre-norm residual blocks, GELU MLP.

``get_symbol`` returns the LM-loss head over (batch, seq) int tokens with
next-token labels.
"""
from __future__ import annotations

from .. import symbol as sym


def transformer_block(x, idx, d_model, num_heads, d_ff,
                      seq_parallel=False, moe_experts=0, moe_top_k=2,
                      expert_parallel=False, moe_capacity_factor=1.25,
                      dropout=0.0):
    """Pre-norm block: x + Drop(MHA(LN(x))); x + Drop(MLP(LN(x))).

    With ``moe_experts > 0`` the MLP is a top-k routed
    mixture-of-experts (``MoE`` op); returns ``(x, aux_loss_sym)``.
    ``dropout`` applies residual dropout after the attention and MLP
    sublayers (the GPT placement)."""
    h = sym.LayerNorm(x, name="blk%d_ln1" % idx)
    h = sym.MultiHeadAttention(h, num_heads=num_heads, causal=True,
                               seq_parallel=seq_parallel,
                               name="blk%d_attn" % idx)
    if dropout:
        h = sym.Dropout(h, p=dropout, name="blk%d_drop1" % idx)
    x = x + h
    h = sym.LayerNorm(x, name="blk%d_ln2" % idx)
    aux = None
    if moe_experts:
        moe = sym.MoE(h, num_experts=moe_experts, top_k=moe_top_k,
                      hidden_size=d_ff, expert_parallel=expert_parallel,
                      capacity_factor=moe_capacity_factor,
                      name="blk%d_moe" % idx)
        h, aux = moe[0], moe[1]
    else:
        h = sym.FullyConnected(h, num_hidden=d_ff, flatten=False,
                               name="blk%d_ffn1" % idx)
        h = sym.Activation(h, act_type="gelu", name="blk%d_gelu" % idx)
        h = sym.FullyConnected(h, num_hidden=d_model, flatten=False,
                               name="blk%d_ffn2" % idx)
    if dropout:
        h = sym.Dropout(h, p=dropout, name="blk%d_drop2" % idx)
    return x + h, aux


def get_symbol(vocab_size=32000, num_layers=12, d_model=768, num_heads=12,
               d_ff=None, seq_len=1024, seq_parallel=False,
               moe_experts=0, moe_top_k=2, moe_aux_coef=0.01,
               expert_parallel=False, moe_capacity_factor=1.25,
               dropout=0.0, **kwargs):
    """``seq_parallel=True`` runs every attention via ring attention over
    the active mesh's 'seq' axis (long-context training: T shards over
    chips, K/V rotate on ICI).

    ``moe_experts=E`` swaps every block's MLP for a top-k routed
    mixture-of-experts; the per-block load-balancing losses are
    AVERAGED over blocks (so ``moe_aux_coef`` keeps the same meaning at
    any depth), scaled by ``moe_aux_coef``, and attached as a
    ``MakeLoss`` head next to the LM loss (so ``Module.fit`` trains
    both).
    ``expert_parallel=True`` additionally shards tokens + experts over
    the active mesh's 'expert' axis (dispatch on ICI all_to_all)."""
    d_ff = d_ff or 4 * d_model
    data = sym.Variable("data")          # (N, T) token ids
    label = sym.Variable("softmax_label")
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=d_model,
                      name="tok_embed")
    pos = sym.Variable("pos_embed", shape=(1, seq_len, d_model),
                       init="normal")
    x = sym.broadcast_add(x, pos)
    # MoE aux losses accumulate as a RUNNING sum so the live set at any
    # block boundary stays {activations, scalar} — the fixed-width
    # boundary contract parallel.pipeline.split_symbol cuts at
    aux_total, n_aux = None, 0
    for i in range(num_layers):
        x, aux = transformer_block(x, i, d_model, num_heads, d_ff,
                                   seq_parallel=seq_parallel,
                                   moe_experts=moe_experts,
                                   moe_top_k=moe_top_k,
                                   expert_parallel=expert_parallel,
                                   moe_capacity_factor=moe_capacity_factor,
                                   dropout=dropout)
        if aux is not None:
            aux_total = aux if aux_total is None else aux_total + aux
            n_aux += 1
    x = sym.LayerNorm(x, name="final_ln")
    x = sym.Reshape(x, shape=(-1, d_model))
    logits = sym.FullyConnected(x, num_hidden=vocab_size, flatten=False,
                                name="lm_head")
    label_f = sym.Reshape(label, shape=(-1,))
    lm = sym.SoftmaxOutput(logits, label_f, name="softmax",
                           normalization="batch")
    if aux_total is None:
        return lm
    balance = sym.MakeLoss(aux_total * (moe_aux_coef / n_aux),
                           name="moe_balance")
    return sym.Group([lm, balance])


def count_params(vocab_size=32000, num_layers=12, d_model=768,
                 num_heads=12, d_ff=None, seq_len=1024):
    """Analytic parameter count (for MFU accounting)."""
    d_ff = d_ff or 4 * d_model
    per_block = (3 * d_model * d_model + 3 * d_model      # qkv
                 + d_model * d_model + d_model            # attn out
                 + d_model * d_ff + d_ff                  # ffn1
                 + d_ff * d_model + d_model               # ffn2
                 + 4 * d_model)                           # 2 LN
    return (vocab_size * d_model                          # tok embed
            + seq_len * d_model                           # pos embed
            + num_layers * per_block
            + 2 * d_model                                 # final LN
            + d_model * vocab_size + vocab_size)          # lm head
