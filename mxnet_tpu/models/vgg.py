"""VGG 11/13/16/19 (reference ``example/image-classification/symbols/vgg.py``)."""
from ..base import MXNetError
from .. import symbol as sym

_CFG = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_symbol(num_classes=1000, num_layers=16, batch_norm=False, **kwargs):
    if num_layers not in _CFG:
        raise MXNetError("vgg depth must be one of %s" % sorted(_CFG))
    layers, filters = _CFG[num_layers]
    body = sym.Variable("data")
    for i, num in enumerate(layers):
        for j in range(num):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=filters[i],
                                   name="conv%d_%d" % (i + 1, j + 1))
            if batch_norm:
                body = sym.BatchNorm(body, name="bn%d_%d" % (i + 1, j + 1))
            body = sym.Activation(body, act_type="relu")
        body = sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")
    flatten = sym.Flatten(body)
    fc6 = sym.FullyConnected(flatten, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu")
    drop6 = sym.Dropout(relu6, p=0.5)
    fc7 = sym.FullyConnected(drop6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu")
    drop7 = sym.Dropout(relu7, p=0.5)
    fc8 = sym.FullyConnected(drop7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(fc8, name="softmax")
