"""Module API (reference ``python/mxnet/module/``)."""
from .base_module import BaseModule, BatchEndParam
from .module import Module
from .bucketing_module import BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule", "BatchEndParam"]
