"""Module API (reference ``python/mxnet/module/``)."""
from .base_module import BaseModule, BatchEndParam
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import (SequentialModule, PythonModule,
                                PythonLossModule)

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PythonModule", "PythonLossModule", "BatchEndParam"]
