"""Module API (reference ``python/mxnet/module/``)."""
from .base_module import BaseModule, BatchEndParam
from .module import Module

__all__ = ["BaseModule", "Module", "BatchEndParam"]
