"""BaseModule — the training-loop contract.

Reference: ``python/mxnet/module/base_module.py`` (``fit`` epoch loop at
``:376,:476-496``: forward_backward → update → update_metric; ``score``,
``predict``, param get/set, checkpointing hooks).  Semantics preserved;
the compute under it is XLA instead of engine-pushed closures.
"""
from __future__ import annotations

import logging
import signal
import sys
import threading
import time

from ..base import MXNetError, StepHung, TrainingDiverged, TrainingPreempted
from .. import metric as metric_mod
from .. import io as io_mod
from ..ndarray import NDArray

__all__ = ["BaseModule"]


def _as_metric(m):
    return m if isinstance(m, metric_mod.EvalMetric) else metric_mod.create(m)


class _PreemptionGuard:
    """SIGTERM/SIGINT watcher for the duration of one ``fit``.

    The handler only records the signal (the async-signal-safe minimum);
    the training loop polls ``fired`` at batch boundaries, where params/
    optimizer state are consistent, drains the prefetch pipeline, writes
    the final checkpoint, and raises :class:`TrainingPreempted`.  Python
    only allows signal handlers on the main thread, so installation is a
    no-op elsewhere (a fit running on a worker thread trains exactly as
    before).  Previous handlers are restored on exit."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled=True):
        self.fired = None
        self._prev = {}
        self._enabled = enabled and \
            threading.current_thread() is threading.main_thread()

    def __enter__(self):
        if self._enabled:
            for sig in self.SIGNALS:
                try:
                    self._prev[sig] = signal.signal(sig, self._record)
                except (ValueError, OSError):  # embedded interpreter etc.
                    pass
        return self

    def _record(self, signum, frame):
        self.fired = signum

    def __exit__(self, exc_type, exc, tb):
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        return False


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- things subclasses implement -----------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def _epoch_end_sync(self):
        """Epoch-boundary synchronization hook (dist_async averaging
        round); default no-op."""

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- shared conveniences -------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    @property
    def symbol(self):
        return self._symbol

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Evaluate on a data iterator (reference ``BaseModule.score``)."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=eval_metric, locals=locals()))
            actual_num_batch += 1
        if score_end_callback is not None:
            for cb in _as_list(score_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                 eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run forward over an iterator, concatenating outputs (reference
        ``BaseModule.predict``)."""
        from ..ndarray import concat

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outputs = [out[0:out.shape[0] - pad]
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError(
                        "Cannot merge batches: different number of outputs")
            merged = [concat([out[i] for out in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, param_sharding=None, compute_dtype=None,
            prefetch_to_device=None, prefetch_depth=2,
            metric_sync_period=None, steps_per_call=None,
            checkpoint=None, checkpoint_period=1, resume_from=None,
            health=None, loss_scale=None, step_timeout_s=None,
            zero=None, plan=None, elastic=None):
        """The training loop (reference ``BaseModule.fit``,
        ``base_module.py:376``), pipelined: by default the train iterator
        is wrapped in :class:`~mxnet_tpu.io.DevicePrefetchIter` so batch
        ``n+1`` stages host→device while batch ``n``'s step executes, and
        the loop itself never blocks on device results between steps (JAX
        async dispatch) except where a metric value is actually read.

        extra knobs (all also settable by env var):

        * ``prefetch_to_device`` — wrap ``train_data`` for background
          device staging (default: ``MXNET_FIT_PIPELINE``, on).  Pass an
          already-wrapped ``DevicePrefetchIter`` as ``train_data`` to
          control staging parameters yourself.
        * ``prefetch_depth`` — staging ring depth (≥2 for double
          buffering).
        * ``metric_sync_period`` — accumulate (label, pred) device refs
          and fold them into the metric every N batches instead of every
          batch (``MXNET_METRIC_SYNC_PERIOD``); a ``Speedometer`` reading
          the metric still sees up-to-date values (reads force a flush).
        * ``steps_per_call`` — dispatch K optimizer steps as one device
          call (``lax.scan`` over a packed super-batch staged by the
          prefetcher); requires the fused step (``MXNET_STEPS_PER_CALL``).

        fault tolerance (see ``docs/fault_tolerance.md``):

        * ``checkpoint`` — a
          :class:`~mxnet_tpu.checkpoint.CheckpointManager` (or a
          directory path for one with defaults).  Epoch-end checkpoints
          are written every ``checkpoint_period`` epochs, and a SIGTERM/
          SIGINT arriving mid-run stops the loop at the next batch
          boundary, writes a final mid-epoch checkpoint, and raises
          :class:`~mxnet_tpu.base.TrainingPreempted`.
        * ``resume_from`` — a ``CheckpointState``/``CheckpointManager``/
          prefix string/``(prefix, epoch)`` pair (see
          :func:`~mxnet_tpu.checkpoint.resolve_resume`): params, aux,
          optimizer states and update counters are restored and the data
          stream is fast-forwarded to the recorded position, so the run
          continues the uninterrupted trajectory.

        run health (see ``docs/health_monitoring.md``):

        * ``health`` — enable the run-health sentinel: True, a policy
          string ('warn'/'skip'/'rollback'), or a configured
          :class:`~mxnet_tpu.health.HealthMonitor`
          (``MXNET_HEALTH_MONITOR=1``).  The fused step then computes a
          global grad norm + non-finite flag on-device, skips poisoned
          steps bit-exactly, and — under the 'rollback' policy with a
          ``checkpoint`` manager — reloads last-good and backs off the
          learning rate on sustained divergence, raising
          :class:`~mxnet_tpu.base.TrainingDiverged` when recovery is
          exhausted.
        * ``loss_scale`` — 'dynamic', a fixed scale, or a
          :class:`~mxnet_tpu.health.DynamicLossScaler` for low-precision
          ``compute_dtype`` runs (``MXNET_LOSS_SCALE``).
        * ``step_timeout_s`` — arm a step watchdog
          (``MXNET_STEP_TIMEOUT_S``): a step making no progress for this
          long dumps all-thread stacks + health stats to an artifact and
          raises :class:`~mxnet_tpu.base.StepHung` instead of hanging.
        * ``zero`` — 'auto' | 'on' | 'off' | '3': ZeRO-style sharding of
          the optimizer state and the weight update over the mesh's
          data axis; '3' additionally keeps the parameters themselves
          at rest as flat 1/N tiles, re-gathered bucket by bucket
          inside each step (``MXNET_ZERO``; see
          ``docs/performance.md``).
        * ``plan`` — a :class:`~mxnet_tpu.parallel.ParallelPlan` or its
          spec string (``"data=4,model=2,zero=3"``): ONE declaration
          composing TP x PP x DP/ZeRO over a named mesh
          (``MXNET_PLAN``; see ``docs/performance.md`` "Composing
          parallelisms").
        * ``elastic`` — live elasticity: True (or ``MXNET_ELASTIC=1``,
          or a configured
          :class:`~mxnet_tpu.parallel.elastic.ElasticCoordinator`)
          polls for scale events at every batch boundary — SIGUSR1, a
          dead peer, or a ``tools/launch.py --scale-event`` manifest —
          and migrates the run in memory (quiesce / re-form / reshard /
          resume) instead of dying; a failed migration falls back to
          the last ``checkpoint``.  See ``docs/fault_tolerance.md``
          "Live elasticity".
        """
        from ..base import get_env
        from ..initializer import Uniform
        from .. import checkpoint as ckpt_mod

        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = Uniform(0.01)

        mgr = None
        if checkpoint is not None:
            mgr = checkpoint \
                if isinstance(checkpoint, ckpt_mod.CheckpointManager) \
                else ckpt_mod.CheckpointManager(str(checkpoint))

        resume_state = None
        if resume_from is not None:
            resume_state = ckpt_mod.resolve_resume(resume_from)
            # checkpointed params take over; whatever the caller passed
            # was the cold-start initialization this run supersedes
            arg_params = resume_state.arg_params
            aux_params = resume_state.aux_params
            force_init = True
            begin_epoch = resume_state.epoch
            self.logger.info(
                "resuming fit from %r: epoch %d, batch offset %d, "
                "num_update %d", resume_state.prefix or resume_from,
                resume_state.epoch, resume_state.nbatch,
                resume_state.num_update)

        K = max(1, int(steps_per_call if steps_per_call is not None
                       else get_env("MXNET_STEPS_PER_CALL", 1, int)))
        if K > 1 and monitor is not None:
            raise MXNetError(
                "steps_per_call > 1 is incompatible with a Monitor: the "
                "monitor needs the per-node executor path, which has no "
                "scanned multi-step form")

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        opt_kwargs = {}
        if param_sharding is not None:
            # only Module.init_optimizer knows this kwarg; BucketingModule
            # and PythonModule keep the base signature
            opt_kwargs["param_sharding"] = param_sharding
        if compute_dtype is not None:
            opt_kwargs["compute_dtype"] = compute_dtype
        if K > 1:
            opt_kwargs["steps_per_call"] = K
        if health is not None:
            opt_kwargs["health"] = health
        if loss_scale is not None:
            opt_kwargs["loss_scale"] = loss_scale
        if zero is not None:
            opt_kwargs["zero"] = zero
        if plan is not None:
            opt_kwargs["plan"] = plan
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params, **opt_kwargs)
        # env-driven activation (MXNET_HEALTH_MONITOR=1) happens inside
        # Module.init_optimizer; modules without health support simply
        # have no monitor
        hmon = getattr(self, "_health_monitor", None)

        from ..parallel.elastic import maybe_coordinator
        elastic = maybe_coordinator(elastic)

        if mgr is not None and mgr.kvstore is None:
            # the manager inherits rank/barrier semantics from the store
            # the fit actually trains against
            mgr.kvstore = getattr(self, "_kvstore", None)
        if resume_state is not None:
            self._restore_from(resume_state)
            # fast-forward the RAW iterator before the staging wrap: the
            # prefetch worker starts pulling batches at construction
            self._fast_forward_data(train_data, resume_state.epoch,
                                    resume_state.nbatch)

        # AOT warmup: lower+compile the fused step in the background so
        # XLA compilation overlaps the prefetch-iterator spin-up below
        # instead of landing serially inside the first step
        # (MXNET_AOT_WARMUP=0 restores the lazy first-call compile)
        compile_thread = None
        if get_env("MXNET_AOT_WARMUP", True, bool) and \
                hasattr(self, "prepare_compiled"):
            import threading

            def _warmup():
                try:
                    self.prepare_compiled()
                except Exception as e:
                    # warmup is an optimization: the lazy path compiles
                    # on the first step exactly as before
                    self.logger.debug("AOT warmup unavailable: %s", e)

            compile_thread = threading.Thread(
                target=_warmup, name="mxtpu-aot-compile", daemon=True)
            compile_thread.start()

        # wrap AFTER init_optimizer: staging placement follows the mesh
        # the optimizer decided on (kvstore type → mesh)
        pipeline = prefetch_to_device
        if pipeline is None:
            pipeline = get_env("MXNET_FIT_PIPELINE", True, bool)
        fit_data = train_data
        if pipeline or K > 1:
            # packed super-batches only exist via the staging iter, so
            # K > 1 forces the wrap even if pipelining was switched off
            ctx = getattr(self, "_context", None)
            if isinstance(ctx, (list, tuple)):  # BucketingModule keeps a bare Context
                ctx = ctx[0] if ctx else None
            fit_data = io_mod.prefetch_to_device(
                train_data, prefetch_depth=prefetch_depth,
                mesh=getattr(self, "_mesh", None), context=ctx,
                steps_per_call=K)

        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)
        sync = int(metric_sync_period if metric_sync_period is not None
                   else get_env("MXNET_METRIC_SYNC_PERIOD", 1, int))
        if sync > 1:
            eval_metric = metric_mod.LazyEvalMetric(eval_metric,
                                                    sync_period=sync)

        timeout = float(step_timeout_s if step_timeout_s is not None
                        else get_env("MXNET_STEP_TIMEOUT_S", 0.0, float))
        watchdog = None
        if timeout > 0:
            from ..health import StepWatchdog

            watchdog = StepWatchdog(
                timeout,
                stats_cb=hmon.snapshot if hmon is not None else None)
            watchdog.start()

        if compile_thread is not None:
            # the first step needs the compiled executable anyway; a
            # bounded join keeps a wedged compile from hanging fit
            # silently (the watchdog covers the in-step hang case)
            compile_thread.join(
                get_env("MXNET_AOT_WARMUP_TIMEOUT_S", 600.0, float))

        try:
            self._fit_epochs(fit_data, eval_data, eval_metric,
                             validation_metric, monitor,
                             batch_end_callback, epoch_end_callback,
                             eval_end_callback, eval_batch_end_callback,
                             begin_epoch, num_epoch, K,
                             mgr=mgr, checkpoint_period=checkpoint_period,
                             resume_nbatch=resume_state.nbatch
                             if resume_state is not None else 0,
                             hmon=hmon, watchdog=watchdog, elastic=elastic)
            if mgr is not None:
                # drain the async checkpoint writer before declaring the
                # fit done: a failed background write must fail the fit,
                # not vanish with the daemon thread
                mgr.flush()
        except StepHung as e:
            # the watchdog delivers a BARE StepHung through
            # PyThreadState_SetAsyncExc (the C API cannot pass
            # arguments); rehydrate the message and artifact path it
            # recorded before raising
            if e.args and e.args[0]:
                raise
            from ..health import last_hang_details

            d = last_hang_details()
            raise StepHung(
                d.get("msg") or "training step made no progress (step "
                "watchdog fired)", note=d.get("note"),
                dump_path=d.get("dump_path")) from None
        finally:
            if watchdog is not None:
                watchdog.stop()
            if fit_data is not train_data:
                # the staging worker must not outlive fit: it would keep
                # consuming the caller's iterator (stealing the batches a
                # follow-up fit/score would read) and can sit inside a
                # device_put when the interpreter tears the runtime down
                in_flight = sys.exc_info()[0] is not None
                try:
                    fit_data.close()
                except Exception:
                    # close() re-raises worker errors the loop never saw;
                    # surface them on a clean exit, but never let them
                    # mask the exception already propagating
                    if not in_flight:
                        raise
                    self.logger.exception(
                        "prefetch close() failed during fit teardown; "
                        "keeping the original error")
                train_data.reset()

    def _fit_epochs(self, fit_data, eval_data, eval_metric,
                    validation_metric, monitor, batch_end_callback,
                    epoch_end_callback, eval_end_callback,
                    eval_batch_end_callback, begin_epoch, num_epoch, K,
                    mgr=None, checkpoint_period=1, resume_nbatch=0,
                    hmon=None, watchdog=None, elastic=None):
        from ..testing import faults

        period = max(1, int(checkpoint_period))
        with _PreemptionGuard() as guard:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                # a resumed mid-epoch run keeps counting from its recorded
                # offset so a second preemption checkpoints the true
                # position (the metric only covers the replayed remainder)
                nbatch = resume_nbatch if epoch == begin_epoch else 0
                data_iter = iter(fit_data)
                end_of_batch = False
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    # a resume checkpoint taken right after an epoch's
                    # final batch fast-forwards past the whole epoch;
                    # run the epoch tail and move on
                    end_of_batch = True
                while not end_of_batch:
                    data_batch = next_data_batch
                    if watchdog is not None:
                        watchdog.kick("epoch %d batch %d" % (epoch, nbatch))
                    faults.inject("step")
                    if monitor is not None:
                        monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    if hmon is not None:
                        # dispatch boundary: feed the monitor this step's
                        # device stats refs; it realizes LAGGED entries
                        # (already finished on device — free reads) and
                        # may request a rollback
                        self._health_tick(hmon, mgr, epoch, nbatch)
                    # lookahead next() AFTER dispatch: pulling batch n+1 off
                    # the staging queue (and refilling it) overlaps the step
                    # that is still executing asynchronously on device
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
                    if K > 1:
                        outs = self.get_outputs()
                        labels = data_batch.label or []
                        for k in range(K):
                            self.update_metric(eval_metric,
                                               [l[k] for l in labels],
                                               outputs=[o[k] for o in outs])
                    else:
                        self.update_metric(eval_metric, data_batch.label)
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        for cb in _as_list(batch_end_callback):
                            cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                             eval_metric=eval_metric,
                                             locals=locals()))
                    nbatch += K
                    if guard.fired is not None:
                        # batch boundary: params/optimizer state consistent
                        self._preempt(guard.fired, fit_data, mgr,
                                      epoch, nbatch)
                    if elastic is not None:
                        event = elastic.poll()
                        if event is not None:
                            self._elastic_migrate(elastic, event, mgr,
                                                  fit_data, epoch, nbatch)
                            # the stream was re-seeked to this boundary
                            # (migration) or left in place (fallback);
                            # either way the lookahead batch fetched
                            # above predates the move — refetch
                            data_iter = iter(fit_data)
                            end_of_batch = False
                            try:
                                next_data_batch = next(data_iter)
                            except StopIteration:
                                end_of_batch = True

                if watchdog is not None:
                    # the epoch tail (eval pass, checkpoint write,
                    # callbacks) is not step progress; the first kick of
                    # the next epoch rearms the timer
                    watchdog.pause()
                if hmon is not None:
                    # drain the lag queue BEFORE the epoch checkpoint: a
                    # pending rollback must not see a freshly saved
                    # diverged state as "last good"
                    self._health_tick(hmon, mgr, epoch, nbatch,
                                      flush=True)

                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 time.time() - tic)

                self._epoch_end_sync()
                arg_params_, aux_params_ = self.get_params()
                self.set_params(arg_params_, aux_params_)

                if mgr is not None and ((epoch + 1) % period == 0
                                        or epoch + 1 == num_epoch):
                    mgr.save(self, epoch=epoch + 1, nbatch=0)

                if epoch_end_callback is not None:
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_params_, aux_params_)

                if guard.fired is not None:
                    # signal landed in the epoch tail: skip eval and stop
                    # at the epoch boundary (tag = completed epochs)
                    self._preempt(guard.fired, fit_data, mgr, epoch + 1, 0)

                if eval_data is not None:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                fit_data.reset()

    # -- fault tolerance hooks ------------------------------------------
    def _preempt(self, signum, fit_data, mgr, epoch, nbatch):
        """Shut the pipeline down, write the final checkpoint, and raise
        :class:`TrainingPreempted` carrying the checkpointed position."""
        self.logger.warning(
            "signal %d received: stopping training at epoch %d, batch %d%s",
            signum, epoch, nbatch,
            "" if mgr is None else "; writing final checkpoint")
        close = getattr(fit_data, "close", None)
        if close is not None:
            try:
                # drain the staging worker first so the checkpoint write
                # does not race an in-flight device_put
                close()
            except Exception:
                self.logger.exception(
                    "prefetch teardown failed during preemption; "
                    "continuing to the checkpoint write")
        if mgr is not None:
            mgr.save(self, epoch=epoch, nbatch=nbatch)
            # the preemption latch is the last code to run before the
            # process exits: drain the async writer so the final
            # checkpoint is on disk (and its errors surfaced) before
            # TrainingPreempted unwinds
            mgr.flush()
        raise TrainingPreempted(
            "training preempted by signal %d at epoch %d, batch %d%s"
            % (signum, epoch, nbatch,
               "; checkpoint written under %r" % mgr.prefix
               if mgr is not None else " (no checkpoint manager "
               "configured — pass fit(checkpoint=...) to save on "
               "preemption)"),
            epoch=epoch, nbatch=nbatch, signum=signum)

    def _elastic_migrate(self, elastic, event, mgr, fit_data, epoch,
                         nbatch):
        """Run one live plan migration at the batch boundary
        ``(epoch, nbatch)``; any mid-migration failure falls back to the
        last good checkpoint so the job is always either migrated or
        resumable — never wedged half-moved.  A retirement
        (:class:`TrainingPreempted` from a shrink) propagates: that rank
        is leaving on purpose, with its quiesce checkpoint written."""
        try:
            return elastic.migrate(self, event, epoch=epoch, nbatch=nbatch,
                                   train_data=fit_data, checkpoint=mgr)
        except (TrainingPreempted, KeyboardInterrupt):
            raise
        except Exception as e:
            if mgr is None or mgr.latest() is None:
                raise
            self.logger.warning(
                "elastic: migration failed mid-flight (%s: %s); falling "
                "back to the last good checkpoint", type(e).__name__, e)
            state = mgr.load()
            self.set_params(state.arg_params, state.aux_params)
            self._restore_from(state)
            # _health_rollback semantics: the restored trajectory
            # continues from the CURRENT stream boundary — the stream
            # itself never moved, only the lookahead batch is refetched.
            # The module may sit on EITHER plan here (a resume-phase
            # failure lands after the reshard), so repoint the staging
            # mesh at whatever the module actually runs now
            if hasattr(fit_data, "mesh"):
                fit_data.mesh = getattr(self, "_mesh", None)
            self._fast_forward_data(fit_data, epoch, nbatch)
            elastic.record_fallback(event, e, epoch=epoch, nbatch=nbatch)
            return None

    def _restore_from(self, state):
        """Apply the optimizer side of a resume after ``init_optimizer``:
        load the states file, then pin the update counters on EVERY
        optimizer copy (the module's, the worker-side updater's, and the
        kvstore's pickled clone) so lr schedules and bias correction
        continue from the checkpointed step instead of restarting — on
        both the split path (counts via ``_index_update_count``) and the
        fused path (reads ``num_update`` directly)."""
        if state.states_path is not None and \
                hasattr(self, "load_optimizer_states"):
            self.load_optimizer_states(state.states_path)
        elif getattr(state, "opt_states", None) and \
                hasattr(self, "set_fused_optimizer_states"):
            # ZeRO-sharded states come back from the v2 piece-window
            # format as canonical weight-shaped trees, already assembled
            # across whatever topology wrote them
            self.set_fused_optimizer_states(state.opt_states)
        n = int(state.num_update)
        for o in self._optimizer_copies():
            o.begin_num_update = n
            o.num_update = n
            # lazily refilled from begin_num_update on the next update,
            # which makes the next step number n + 1 on every path
            o._index_update_count = {}

    def _optimizer_copies(self):
        """Every live optimizer object a state change must reach: the
        module's, the worker-side updater's, and the kvstore's pickled
        clone (deduped by identity)."""
        kv = getattr(self, "_kvstore", None)
        opts = []
        for o in (getattr(self, "_optimizer", None),
                  getattr(getattr(self, "_updater", None), "optimizer",
                          None),
                  getattr(kv, "_optimizer", None),
                  getattr(getattr(kv, "updater", None), "optimizer", None)):
            if o is not None and not any(o is seen for seen in opts):
                opts.append(o)
        return opts

    # -- run-health hooks -----------------------------------------------
    def _health_tick(self, hmon, mgr, epoch, nbatch, flush=False):
        """Feed the health monitor at a dispatch boundary and act on its
        verdict.  'skip' needs no action here — the device already kept
        the old params bit-exactly; 'rollback' reloads last-good."""
        stats = getattr(self, "_last_health_stats", None)
        self._last_health_stats = None
        try:
            if flush:
                if stats is not None:
                    hmon.tick(stats, step=(epoch, nbatch))
                action = hmon.flush()
            else:
                action = hmon.tick(stats, step=(epoch, nbatch))
        except TrainingDiverged as e:
            e.epoch, e.nbatch = epoch, nbatch
            raise
        if action == "rollback":
            self._health_rollback(hmon, mgr, epoch, nbatch)

    def _health_rollback(self, hmon, mgr, epoch, nbatch):
        """Reload the last-good checkpoint, back the learning rate off,
        and continue from the CURRENT stream position — the poison
        window is consumed, not replayed (replaying it would diverge
        identically).  No manager or no checkpoint on disk means there
        is nothing to roll back to: typed :class:`TrainingDiverged`."""
        reason = getattr(hmon, "_last_anomaly", "sustained divergence")
        if mgr is None or mgr.latest() is None:
            raise TrainingDiverged(
                "health policy requested a rollback at epoch %d batch %d "
                "(%s) but no checkpoint is available — pass "
                "fit(checkpoint=...) so there is a last-good state to "
                "reload" % (epoch, nbatch, reason),
                epoch=epoch, nbatch=nbatch, reason=reason)
        state = mgr.load()
        hmon.note_rollback(step=(epoch, nbatch))
        factor = hmon.lr_backoff
        self.logger.warning(
            "health: rollback %d/%d at epoch %d batch %d (%s) — "
            "restoring checkpoint epoch %d (num_update %d), learning "
            "rate x%g", hmon.consecutive_rollbacks, hmon.max_rollbacks,
            epoch, nbatch, reason, state.epoch, state.num_update, factor)
        self.set_params(state.arg_params, state.aux_params)
        self._restore_from(state)
        for o in self._optimizer_copies():
            o.lr *= factor
            sch = getattr(o, "lr_scheduler", None)
            if sch is not None:
                # FactorScheduler reads base_lr; Poly/Cosine recompute
                # from base_lr_orig — back both off so every schedule
                # family honors the reduction
                if getattr(sch, "base_lr", None) is not None:
                    sch.base_lr *= factor
                if getattr(sch, "base_lr_orig", None) is not None:
                    sch.base_lr_orig *= factor
        # the restored trajectory has different statistics; the stale
        # EMA/lag state must not re-trigger on it
        hmon.soft_reset()

    def _fast_forward_data(self, train_data, epochs, nbatch):
        """Fast-forward the raw data stream to a mid-run position.

        Seekable pipelines (seeded :class:`~mxnet_tpu.io.NDArrayIter`,
        the data service, seeded :class:`~mxnet_tpu.image.ImageIter`,
        and any prefetch wrapper over them) jump in O(1):
        ``seek(epochs, nbatch)`` recomputes the epoch permutation from
        the seed and places the cursor — no decode, no replay, bit-exact
        at any process count.  Everything else falls back to O(steps)
        replay: one ``reset()`` per completed epoch reproduces the
        shuffle-RNG draw sequence an uninterrupted run performs at its
        epoch boundaries (given the same process-level seeding — see
        ``docs/fault_tolerance.md``), then ``nbatch`` batches are drawn
        and discarded."""
        can_seek = getattr(train_data, "seekable", None)
        if can_seek is not None and can_seek():
            train_data.seek(int(epochs), int(nbatch))
            self.logger.info(
                "resume fast-forward: O(1) seek to epoch %d batch %d",
                int(epochs), int(nbatch))
            return
        for _ in range(int(epochs)):
            train_data.reset()
        for skipped in range(int(nbatch)):
            try:
                train_data.next()
            except StopIteration:
                self.logger.warning(
                    "resume fast-forward exhausted the epoch after %d of "
                    "%d batches; continuing from the epoch boundary",
                    skipped, nbatch)
                break

    def install_monitor(self, monitor):
        raise NotImplementedError

    # -- introspection --------------------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError


class BatchEndParam:
    """Callback payload (reference namedtuple ``BatchEndParam``)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
