"""BucketingModule (reference ``python/mxnet/module/bucketing_module.py``).

Variable-length training without padding waste: ``sym_gen(bucket_key)``
produces a symbol per sequence length, and one Module per bucket is
created lazily, all sharing the default bucket's parameter arrays via the
``shared_module`` bind path (reference: per-bucket executors over one
memory pool, ``bucketing_module.py:35``).

TPU note: each bucket compiles its own XLA program, cached per shape —
exactly the per-bucket-graph recompile the reference's executor cache
amortizes (SURVEY.md §7 "hard parts (b)").  The fused train step is
bypassed (grad arrays must be shared across buckets), so buckets run the
split forward/backward/update path with a shared kvstore/updater.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("please specify default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False
        self.inputs_need_grad = False
        self._grad_req = None
        self._monitor = None

    # -- properties ------------------------------------------------------
    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        res = self._sym_gen(bucket_key)
        if not (isinstance(res, tuple) and len(res) == 3):
            raise MXNetError("sym_gen must return "
                             "(symbol, data_names, label_names)")
        return res

    # -- params ----------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init,
                         allow_extra=allow_extra)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing parameters"
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    # -- bind / bucket switching ----------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded:
            if not force_rebind:
                self.logger.warning("Already bound, ignoring bind()")
                return
            # reference _reset_bind: drop every per-bucket executor —
            # stale modules would keep sharing the OLD default module's
            # parameter arrays
            self._buckets = {}
            self._curr_module = None
            self._curr_bucket_key = None
            self.binded = False
            self.params_initialized = False
            self.optimizer_initialized = False
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True

        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names=data_names,
                        label_names=label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to a bucket, binding a new per-length executor sharing
        the default module's parameters if unseen."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            from ..compile_cache import registry

            # every unseen bucket binds (and compiles) a fresh executor:
            # exactly the per-shape retrace the recompile guard counts
            registry.guard("BucketingModule").observe(
                ((".bucket", (repr(bucket_key)[:120],)),), force=True)
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names=data_names,
                            label_names=label_names, logger=self.logger,
                            context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            if self.params_initialized:
                module.params_initialized = True
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            # share the optimizer/updater machinery so updates keep state
            src = self._buckets[self._default_bucket_key]
            if src.optimizer_initialized:
                self._share_optimizer(src, module)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    @staticmethod
    def _share_optimizer(src, dst):
        dst._optimizer = src._optimizer
        dst._updater = src._updater
        dst._kvstore = src._kvstore
        dst._update_on_kvstore = src._update_on_kvstore
        dst._mesh = src._mesh
        # buckets share parameter ARRAYS; the fused path would need
        # per-bucket donated-state plumbing, so buckets use the split path
        dst._fused = None
        dst._fused_states = None
        dst.optimizer_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, "
                                "ignoring...")
            return
        default = self._buckets[self._default_bucket_key]
        default.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        # the default module may have compiled a fused step; buckets need
        # shared grad arrays, so disable it there too
        default._fused = None
        default._fused_states = None
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                self._share_optimizer(default, mod)
        self.optimizer_initialized = True

    # -- compute ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()

    def _epoch_end_sync(self):
        self._curr_module._epoch_end_sync()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, monitor):
        self._monitor = monitor
        for mod in self._buckets.values():
            mod.install_monitor(monitor)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._curr_module.save_checkpoint(prefix, epoch,
                                          save_optimizer_states)
